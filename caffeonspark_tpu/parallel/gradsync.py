"""Communication-efficient gradient exchange (COS_GRAD_SYNC).

The reference's entire reason to exist was its gradient-exchange design
— `P2PSync` tree reduce inside a node, `SocketSync`/`RDMASync` sharded
all-to-all across nodes — while our `parallel/dp.py` hands the exchange
to GSPMD's default placement: one implicit f32 all-reduce per param,
scheduled wherever the partitioner likes (in practice serialized after
the whole backward).  FireCaffe's scaling analysis (PAPERS.md) says the
cluster wins come from amortizing and *shrinking* that exchange; this
module makes the exchange an explicit, tunable layer:

  COS_GRAD_SYNC=default   byte-identical to the implicit exchange (the
                          module is completely inert — no extra ops are
                          traced, so the HLO is the pre-existing HLO)
  COS_GRAD_SYNC=bucket    bucketed backward-overlap: param blobs group
                          into ~COS_GRAD_BUCKET_MB flat buckets in
                          reverse-backward (grad-completion) order; a
                          `jax.custom_vjp` hook per bucket re-emits the
                          bucket's cotangents through one flat buffer
                          and pins a replication sharding-constraint on
                          it RIGHT THERE, mid-backward — so the GSPMD
                          all-reduce for bucket k is issued while bucket
                          k+1's grads are still computing (XLA's async
                          collectives overlap it with the remaining
                          backward on real ICI/DCN)
  COS_GRAD_SYNC=quant     bucket + low-precision wire: the flat bucket
                          is cast to COS_GRAD_WIRE_DTYPE (bfloat16
                          default; int8 adds a per-bucket max-abs scale
                          and stochastic rounding) before the
                          replication constraint and cast back to the
                          grad dtype after — f32 master accumulation in
                          the optimizer is untouched, only the wire
                          payload shrinks (sp.py precision-floor rule:
                          anything CONSUMING the reduced value stays
                          full precision)
  COS_GRAD_SYNC=hier      bucket + hierarchical two-phase exchange: the
                          flat bucket is constrained to a dp-sharded
                          layout first (reduce-scatter placement) and
                          replicated second (all-gather) — the standard
                          reduce-scatter + all-gather decomposition,
                          which XLA maps intra-ring first on multihost
                          meshes so the slow cross-host hop carries
                          1/local of the traffic
  COS_GRAD_SYNC=auto      numerics-safe pick for the topology: hier on
                          multi-process dp meshes, bucket on
                          single-process dp>1 meshes, default otherwise

Mechanism notes (honest about what GSPMD lets us control):

  * Grads arriving out of `jax.value_and_grad` are LOGICALLY already
    the global gradient — the partitioner decides where the physical
    all-reduce happens.  A `with_sharding_constraint` on the bucket's
    flat buffer forces the value to be replicated AT THAT POINT of the
    dataflow graph and in THAT dtype, which is exactly the two levers
    the exchange needs (placement for overlap, dtype for wire size).
  * The custom_vjp hook wraps each bucket's param blobs with an
    identity whose bwd rule fires at the point in the backward where
    the LAST cotangent of the bucket is available — "emit the
    collective as soon as the bucket's grads are final".  Hooks are
    used when iter_size == 1 and the transform is deterministic
    (COS_GRAD_OVERLAP=0 opts out); iter_size > 1 accumulation and the
    rng-consuming int8 path apply the identical transform to the
    finished grad pytree instead (`exchange`), preserving Caffe's
    exchange-once-per-step semantics.
  * int8 quantizes the already-reduced value, i.e. it models an
    exchange whose intra-reduction runs at accumulator precision and
    whose wire payload is int8 + one f32 scale per bucket; convergence
    is gated by tests/test_gradsync.py, not assumed.
  * tp/ep-sharded param blobs (their grads are NOT replicated) and
    BatchNorm stat blobs (never optimized; overwritten by the forward)
    are excluded from buckets and keep today's GSPMD handling.

Every mode composes with TP, ZeRO-1 and the fused K-step loop: the
transform lives inside `Solver.train_step_fn`, which is the scan body
of `build_train_step_many` and the function `ParallelSolver` wraps for
the mesh.
"""

from __future__ import annotations

import os
from typing import Dict, FrozenSet, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

MODES = ("auto", "default", "bucket", "quant", "hier")
WIRE_DTYPES = ("bfloat16", "int8")

_DEFAULT_BUCKET_MB = 25.0     # DDP-style default; COS_GRAD_BUCKET_MB
_INT8_SCALE_BYTES = 4         # one f32 max-abs scale rides per bucket


def env_mode() -> str:
    m = os.environ.get("COS_GRAD_SYNC", "default").strip().lower()
    if m not in MODES:
        raise ValueError(
            f"COS_GRAD_SYNC={m!r}: expected one of {'|'.join(MODES)}")
    return m


def env_bucket_mb() -> float:
    v = os.environ.get("COS_GRAD_BUCKET_MB", "")
    return float(v) if v else _DEFAULT_BUCKET_MB


def env_wire_dtype() -> Optional[str]:
    v = os.environ.get("COS_GRAD_WIRE_DTYPE", "").strip().lower()
    if v and v not in WIRE_DTYPES:
        raise ValueError(
            f"COS_GRAD_WIRE_DTYPE={v!r}: expected one of "
            f"{'|'.join(WIRE_DTYPES)}")
    return v or None


class Bucket(NamedTuple):
    """One exchange unit: blobs whose grads finalize together."""
    index: int
    entries: Tuple[Tuple[str, str], ...]    # (layer, blob) in fire order
    shapes: Tuple[Tuple[int, ...], ...]
    numel: int
    bytes_grad: int                          # at the grad dtype
    bytes_wire: int                          # at the wire dtype


class GradSyncPlan(NamedTuple):
    """Static exchange metadata: what goes on the wire, in what order,
    in what dtype — consumed by the transform, the metrics `comm`
    block, scripts/roofline.py and the bench floor model."""
    mode: str                                # resolved, never "auto"
    wire_dtype: Optional[str]                # None = grad dtype
    bucket_mb: float
    buckets: Tuple[Bucket, ...]
    total_numel: int
    total_bytes_grad: int
    total_bytes_wire: int
    skipped: Tuple[Tuple[str, str], ...]     # blobs left to GSPMD

    @property
    def n_buckets(self) -> int:
        return len(self.buckets)

    def comm_info(self) -> dict:
        """The `comm` block of the PipelineMetrics JSON: per-step
        exchange traffic at a glance."""
        return {
            "mode": self.mode,
            "wire_dtype": self.wire_dtype or "grad",
            "bucket_mb": self.bucket_mb,
            "buckets": self.n_buckets,
            "bucket_bytes_wire": [b.bytes_wire for b in self.buckets],
            "exchanged_params": self.total_numel,
            "bytes_per_step_wire": self.total_bytes_wire,
            "bytes_per_step_dense_f32": self.total_numel * 4,
            "skipped_blobs": len(self.skipped),
        }

    def exposed_wire_bytes(self, local_size: int = 1,
                           hide_bytes: Optional[int] = None) -> int:
        """Modeled NON-HIDDEN wire bytes per step, for the injected
        comm floor (scripts/bench_gradsync.py).  `default` serializes
        the whole dense exchange after backward.  Overlap modes hide
        buckets under the remaining backward compute — fully when
        `hide_bytes` is None, else up to that capacity (the wire can
        only carry so much while the backward runs) — except the
        LAST-fired bucket (the first-layer one: nothing is left to
        hide under), the standard DDP overlap model.  `hier` divides
        every wire quantity by the modeled intra-host group size
        first: the slow cross-host hop carries 1/local of the bytes
        after the intra-host reduce-scatter.  The floor=0 control run
        in the bench artifact is the reality check on this model."""
        div = max(1, int(local_size)) if self.mode == "hier" else 1
        total = -(-self.total_bytes_wire // div)
        if self.mode == "default":
            return total
        last = (-(-self.buckets[-1].bytes_wire // div)
                if self.buckets else 0)
        if hide_bytes is None:
            return last
        return max(last, total - int(hide_bytes))

    def tier_wire_bytes(self, local_size: int = 1,
                        hide_bytes: Optional[int] = None
                        ) -> Tuple[int, int]:
        """(intra_host, inter_host) modeled exposed wire bytes per
        step — the two-tier split behind the asymmetric comm floor
        (COS_FAULT_COMM_INTRA_NS_PER_BYTE, scripts/bench_scaling.py).
        Flat modes put every exposed byte on the slow inter-host link:
        (0, exposed).  `hier` is the FireCaffe-style two-tier
        exchange: the inter-host leg carries the post-reduce-scatter
        1/local slice (exactly `exposed_wire_bytes`), and the
        intra-host reduce-scatter + all-gather together move ~2× the
        exposed single-link bytes over the fast local links — 0 when
        the host holds a single rank (nothing to reduce locally).
        With local_size=1 or a zero intra price this reduces to the
        single-tier model, so the existing floor maths are
        unchanged."""
        inter = self.exposed_wire_bytes(local_size=local_size,
                                        hide_bytes=hide_bytes)
        if self.mode != "hier" or max(1, int(local_size)) <= 1:
            return (0, inter)
        intra = 2 * self.exposed_wire_bytes(local_size=1,
                                            hide_bytes=hide_bytes)
        return (intra, inter)

    @property
    def n_messages(self) -> int:
        """Wire messages per step (per-message latency floor term)."""
        return 1 if self.mode == "default" else self.n_buckets


def _wire_for(mode: str, wire_env: Optional[str]) -> Optional[str]:
    """quant defaults to bf16 wire; hier honors an explicit wire dtype
    but stays at grad dtype otherwise; bucket/default never recast."""
    if mode == "quant":
        return wire_env or "bfloat16"
    if mode == "hier":
        return wire_env
    return None


def build_plan(net, mode: str, *, bucket_mb: Optional[float] = None,
               wire_dtype: Optional[str] = None,
               skip_blobs: FrozenSet[Tuple[str, str]] = frozenset()
               ) -> GradSyncPlan:
    """Bucket the net's param blobs in reverse-backward order (the
    order their grads finalize: last compute layer first).

    No env reads here: `plan` is built lazily, possibly from inside a
    traced `attach`/`exchange` (coslint COS003) — the COS_GRAD_BUCKET_MB
    knob is resolved once at GradSync construction and passed in."""
    bucket_mb = _DEFAULT_BUCKET_MB if bucket_mb is None else bucket_mb
    wire = _wire_for(mode, wire_dtype)
    grad_itemsize = jnp.dtype(net.dtype).itemsize
    wire_itemsize = (1 if wire == "int8" else
                     2 if wire == "bfloat16" else grad_itemsize)
    stat = set(net.stat_param_layers())
    skipped: List[Tuple[str, str]] = []
    order: List[Tuple[str, str, Tuple[int, ...]]] = []
    for lp in reversed(net.compute_layers):
        specs = net.param_layout.get(lp.name)
        if not specs:
            continue
        for bname, shape, _ in reversed(specs):
            if lp.name in stat or (lp.name, bname) in skip_blobs:
                skipped.append((lp.name, bname))
            else:
                order.append((lp.name, bname, tuple(shape)))

    cap = max(1, int(bucket_mb * (1 << 20)))
    buckets: List[Bucket] = []
    cur: List[Tuple[str, str, Tuple[int, ...]]] = []
    cur_bytes = 0

    def _flush():
        nonlocal cur, cur_bytes
        if not cur:
            return
        numel = sum(int(np.prod(s)) if s else 1 for _, _, s in cur)
        wire_b = numel * wire_itemsize + (
            _INT8_SCALE_BYTES if wire == "int8" else 0)
        buckets.append(Bucket(
            index=len(buckets),
            entries=tuple((ln, bn) for ln, bn, _ in cur),
            shapes=tuple(s for _, _, s in cur),
            numel=numel, bytes_grad=numel * grad_itemsize,
            bytes_wire=wire_b))
        cur, cur_bytes = [], 0

    for ln, bn, shape in order:
        n = int(np.prod(shape)) if shape else 1
        if cur and cur_bytes + n * grad_itemsize > cap:
            _flush()
        cur.append((ln, bn, shape))
        cur_bytes += n * grad_itemsize
    _flush()

    total_numel = sum(b.numel for b in buckets)
    return GradSyncPlan(
        mode=mode, wire_dtype=wire, bucket_mb=float(bucket_mb),
        buckets=tuple(buckets), total_numel=total_numel,
        total_bytes_grad=total_numel * grad_itemsize,
        total_bytes_wire=sum(b.bytes_wire for b in buckets),
        skipped=tuple(skipped))


# ---------------------------------------------------------------------------
def quantize_int8(flat: Array, rng: Optional[Array]
                  ) -> Tuple[Array, Array]:
    """Per-bucket symmetric int8: max-abs scale + stochastic rounding
    (unbiased — E[q·scale] = flat; plain round-to-nearest when no rng
    is supplied).  Returns (q_int8, f32_scale)."""
    f = flat.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(f)), 1e-30) / 127.0
    x = f / scale
    if rng is not None:
        x = jnp.floor(x + jax.random.uniform(rng, x.shape, x.dtype))
    else:
        x = jnp.round(x)
    return jnp.clip(x, -127.0, 127.0).astype(jnp.int8), scale


def dequantize_int8(q: Array, scale: Array, dtype) -> Array:
    return (q.astype(jnp.float32) * scale).astype(dtype)


class GradSync:
    """The exchange itself: bucketing + wire transform + collective
    placement, applied either via backward hooks (`attach`, fires
    per-bucket mid-backward) or on the finished grad pytree
    (`exchange`).  Both paths run the identical per-bucket transform.

    Inert (`enabled` False) in `default` mode: neither path adds a
    single op, so the traced program is byte-identical to the
    pre-gradsync step."""

    def __init__(self, net, *, mode: Optional[str] = None,
                 bucket_mb: Optional[float] = None,
                 wire_dtype: Optional[str] = None,
                 overlap: Optional[bool] = None):
        self.net = net
        self.requested = env_mode() if mode is None else mode
        if self.requested not in MODES:
            raise ValueError(f"grad-sync mode {self.requested!r}: "
                             f"expected one of {'|'.join(MODES)}")
        # resolved HERE, not in build_plan: the plan may be built
        # lazily at trace time, where an env read would be baked into
        # the compiled program (coslint COS003)
        self._bucket_mb = (env_bucket_mb() if bucket_mb is None
                           else float(bucket_mb))
        self._wire_env = (env_wire_dtype() if wire_dtype is None
                          else wire_dtype)
        if overlap is None:
            overlap = os.environ.get("COS_GRAD_OVERLAP", "1") != "0"
        self.overlap = bool(overlap)
        self.mesh = None
        self._skip: FrozenSet[Tuple[str, str]] = frozenset()
        self._plan: Optional[GradSyncPlan] = None
        self._hooks: Dict[int, object] = {}

    # -- topology ------------------------------------------------------
    def bind_mesh(self, mesh,
                  skip_blobs: FrozenSet[Tuple[str, str]] = frozenset()
                  ) -> "GradSync":
        """Called by ParallelSolver before any step is traced: the mesh
        resolves `auto`, enables the sharding constraints, and excludes
        tp/ep-sharded blobs (their grads are sharded, not replicated —
        bucketing them would force a pessimizing all-gather)."""
        self.mesh = mesh
        self._skip = frozenset(skip_blobs)
        self._plan = None
        self._hooks.clear()
        return self

    @property
    def mode(self) -> str:
        if self.requested != "auto":
            return self.requested
        dp = self.mesh.shape.get("dp", 1) if self.mesh is not None else 1
        if dp <= 1:
            return "default"
        return "hier" if jax.process_count() > 1 else "bucket"

    @property
    def enabled(self) -> bool:
        return self.mode != "default"

    @property
    def plan(self) -> GradSyncPlan:
        if self._plan is None or self._plan.mode != self.mode:
            self._plan = build_plan(self.net, self.mode,
                                    bucket_mb=self._bucket_mb,
                                    wire_dtype=self._wire_env,
                                    skip_blobs=self._skip)
        return self._plan

    @property
    def needs_rng(self) -> bool:
        return self.enabled and self.plan.wire_dtype == "int8"

    def use_hooks(self, iter_size: int) -> bool:
        """Backward hooks need a deterministic bwd rule (no rng) and
        one exchange per optimizer step (iter_size == 1)."""
        return (self.enabled and self.overlap and iter_size <= 1
                and not self.needs_rng)

    # -- the per-bucket wire transform ---------------------------------
    def _dp_on(self) -> bool:
        return (self.mesh is not None
                and self.mesh.shape.get("dp", 1) > 1)

    def _replicate(self, x: Array) -> Array:
        """Pin the exchange point: the value must be replicated (i.e.
        all-reduced) HERE, at x's current dtype."""
        if not self._dp_on():
            return x
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, P()))

    def _two_phase(self, x: Array) -> Array:
        """hier: dp-sharded first (reduce-scatter placement), then
        replicated (all-gather) — the two-phase decomposition XLA maps
        intra-ring first on multihost meshes."""
        if not self._dp_on():
            return x
        from jax.sharding import NamedSharding, PartitionSpec as P
        dp = self.mesh.shape["dp"]
        n = x.shape[0]
        pad = (-n) % dp
        if pad:
            x = jnp.concatenate([x, jnp.zeros((pad,), x.dtype)])
        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, P("dp")))
        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, P()))
        return x[:n] if pad else x

    def _transform_flat(self, flat: Array,
                        rng: Optional[Array]) -> Array:
        mode, wire = self.mode, self.plan.wire_dtype
        orig = flat.dtype
        if wire == "int8":
            q, scale = quantize_int8(flat, rng)
            q = (self._two_phase(q) if mode == "hier"
                 else self._replicate(q))
            return dequantize_int8(q, scale, orig)
        if wire == "bfloat16" and orig != jnp.bfloat16:
            flat = flat.astype(jnp.bfloat16)
        flat = (self._two_phase(flat) if mode == "hier"
                else self._replicate(flat))
        return flat.astype(orig) if flat.dtype != orig else flat

    def _transform_bucket(self, bucket: Bucket, leaves: List[Array],
                          rng: Optional[Array]) -> List[Array]:
        flats = [g.reshape(-1) for g in leaves]
        flat = jnp.concatenate(flats) if len(flats) > 1 else flats[0]
        flat = self._transform_flat(flat, rng)
        out, off = [], 0
        for g, shape in zip(leaves, bucket.shapes):
            n = int(np.prod(shape)) if shape else 1
            out.append(flat[off:off + n].reshape(shape))
            off += n
        return out

    # -- path 1: backward hooks (overlap) ------------------------------
    def _hook(self, bucket: Bucket):
        """custom_vjp identity over the bucket's blobs: fwd passes the
        params through untouched; bwd fires where the bucket's LAST
        cotangent is available and re-emits all of them through the
        flat wire buffer + collective constraint."""
        h = self._hooks.get(bucket.index)
        if h is not None:
            return h

        @jax.custom_vjp
        def hook(*blobs):
            return blobs

        def fwd(*blobs):
            return blobs, None

        def bwd(_, cts):
            return tuple(self._transform_bucket(bucket, list(cts),
                                                None))

        hook.defvjp(fwd, bwd)
        self._hooks[bucket.index] = hook
        return hook

    def attach(self, params: Dict) -> Dict:
        """Wrap params with the per-bucket backward hooks (call inside
        the loss function, on the value being differentiated)."""
        out = {ln: dict(bl) for ln, bl in params.items()}
        for bucket in self.plan.buckets:
            vals = tuple(out[ln][bn] for ln, bn in bucket.entries)
            new = self._hook(bucket)(*vals)
            for (ln, bn), v in zip(bucket.entries, new):
                out[ln][bn] = v
        return out

    # -- path 2: finished-grad transform -------------------------------
    def exchange(self, grads: Dict,
                 rng: Optional[Array] = None) -> Dict:
        """Apply the identical per-bucket transform to a finished grad
        pytree (iter_size accumulation / int8 stochastic rounding)."""
        if not self.enabled:
            return grads
        out = {ln: dict(bl) for ln, bl in grads.items()}
        for bucket in self.plan.buckets:
            sub = (jax.random.fold_in(rng, bucket.index)
                   if rng is not None and self.needs_rng else None)
            leaves = [out[ln][bn] for ln, bn in bucket.entries]
            new = self._transform_bucket(bucket, leaves, sub)
            for (ln, bn), v in zip(bucket.entries, new):
                out[ln][bn] = v
        return out


def make_gradsync(net, **kw) -> GradSync:
    return GradSync(net, **kw)
