"""Sync-mode layer (COS_SYNC_MODE): lockstep | local_sgd | async.

Training so far is synchronous lockstep — every rank joins one
jax.distributed mesh and every step's gradient all-reduce is a fleet-
wide barrier, so one slow or dead rank stalls the whole fleet (the
failure CaffeOnSpark inherited from its peer-to-peer all-reduce).
SparkNet (periodic model averaging) and DeepSpark (asynchronous
updates with explicit staleness bounds on commodity clusters) — both
in PAPERS.md — show that relaxed sync modes recover throughput under
heterogeneous capacity without giving up convergence.  This module is
that relaxation, beside `COS_GRAD_SYNC` (which tunes HOW the lockstep
exchange moves bytes; this layer tunes WHETHER steps synchronize at
all):

  COS_SYNC_MODE=lockstep   today's behavior, byte-identical — no sync
                           object is even constructed, the training
                           path is untouched (the same inertness
                           contract as COS_GRAD_SYNC=default)
  COS_SYNC_MODE=local_sgd  SparkNet-style: each rank runs K local
                           steps (the PR 4 fused loop makes a round
                           ONE dispatch), then the fleet averages
                           parameters once — one exchange per K steps,
                           the ultimate comm amortization.  The round
                           barrier is SOFT: only live ranks within one
                           round of the boundary are waited for (a
                           straggler >1 round behind detaches and
                           adopts the pack average when it arrives;
                           a dead rank drops out after its heartbeat
                           goes stale), so the pack is never stalled.
  COS_SYNC_MODE=async      DeepSpark-style bounded staleness: ranks
                           never barrier at all — each rank merges its
                           params into a versioned global state at
                           least every S steps (S = the staleness
                           bound).  A rank's params are therefore
                           never more than S of its own steps away
                           from the last global sync; if the merge
                           cannot land (lock contention, flaky
                           storage) the rank WAITS and retries — fast
                           ranks proceed up to S steps ahead, then
                           wait on the sync, never on the straggler.

Ranks in the relaxed modes do NOT join a global jax.distributed mesh:
each process trains on its own local devices (any local dp/tp mesh —
COS_GRAD_SYNC still applies to that intra-rank exchange, which is how
the wire modes compose), and the cross-rank exchange is host-side
through a shared-filesystem `ParamStore` in the run's output directory
(NFS on pods — the same shared-storage assumption the supervisor's
snapshot resume already makes).  That is precisely what makes the
fleet ELASTIC: there is no collective to hang when a rank dies, the
pack just stops waiting for it (heartbeat timeout), and a relaunched
rank re-admits itself by adopting the latest averaged state at the
next round (`adopt_latest`).

Knobs (docs/tuning.md has the full table):

  COS_SYNC_MODE                 lockstep (default) | local_sgd | async
  COS_SYNC_K                    local steps per averaging round
                                (local_sgd; default 8)
  COS_SYNC_STALENESS            max local steps between global merges
                                (async; default 8)
  COS_SYNC_ALPHA                async merge weight (default 0 = auto:
                                1/live_ranks)
  COS_SYNC_ROUND_TIMEOUT_S      soft-barrier cap per round (default 30)
  COS_SYNC_HEARTBEAT_TIMEOUT_S  silence before a rank counts as dead
                                (default 10)
  COS_SYNC_WIRE_DTYPE           float32 (default) | bfloat16 — dtype
                                of the published param payload (the
                                gradsync wire-dtype idea applied to
                                the averaging exchange; averaging math
                                stays f32)

Fault injection composes through `tools/chaos.py`: a flaky-exchange
fault makes local_sgd SKIP the round (round semantics tolerate a
missing contribution) but makes async RETRY (the staleness bound is a
promise); flaky-storage faults are absorbed by the store's own retry
loop.
"""

from __future__ import annotations

import io
import json
import os
import time
from typing import Callable, Dict, List, NamedTuple, Optional

import numpy as np

# one repo-wide env-number parser (utils/envutils.py) — strict flavor:
# a mistyped COS_SYNC_* knob is a config error worth failing loudly on
from ..utils.envutils import env_num as _env_num

MODES = ("lockstep", "local_sgd", "async")
WIRE_DTYPES = ("float32", "bfloat16")

# flat param keys are "<layer>::<blob>" (checkpoint.flatten_host_params)
KEY_SEP = "::"

HostFlat = Dict[str, np.ndarray]


def env_sync_mode() -> str:
    m = os.environ.get("COS_SYNC_MODE", "lockstep").strip().lower()
    if m not in MODES:
        raise ValueError(
            f"COS_SYNC_MODE={m!r}: expected one of {'|'.join(MODES)}")
    return m




class SyncPolicy(NamedTuple):
    """Resolved sync-mode configuration (env read once, at startup —
    coslint COS003 discipline, same as GradSync)."""
    mode: str
    k: int                        # local steps per round (local_sgd)
    staleness: int                # max steps between merges (async)
    alpha: float                  # async merge weight (0 = 1/live)
    round_timeout_s: float
    heartbeat_timeout_s: float
    wire_dtype: str
    # COS_SYNC_STORE: where the ParamStore lives.  "" = the shared-
    # filesystem default (<output>/.sync); an http(s):// URL selects
    # the NodeAgent blob transport (no shared filesystem needed)
    store: str = ""

    @property
    def elastic(self) -> bool:
        """Relaxed modes run without a global mesh: ranks may join and
        leave mid-run."""
        return self.mode != "lockstep"

    @property
    def boundary(self) -> int:
        """The iteration interval exchanges happen on — fed to the
        fused-loop chunk schedule so no chunk crosses an exchange."""
        if self.mode == "local_sgd":
            return self.k
        if self.mode == "async":
            return self.staleness
        return 0

    def describe(self) -> dict:
        out = {"mode": self.mode}
        if self.mode == "local_sgd":
            out["k"] = self.k
        if self.mode == "async":
            out["staleness"] = self.staleness
            out["alpha"] = self.alpha or "auto(1/live)"
        if self.elastic:
            out["round_timeout_s"] = self.round_timeout_s
            out["heartbeat_timeout_s"] = self.heartbeat_timeout_s
            out["wire_dtype"] = self.wire_dtype
            if self.store:
                out["store"] = self.store
        return out


def resolve_policy(mode: Optional[str] = None) -> SyncPolicy:
    mode = env_sync_mode() if mode is None else mode
    if mode not in MODES:
        raise ValueError(f"sync mode {mode!r}: expected one of "
                         f"{'|'.join(MODES)}")
    k = int(_env_num("COS_SYNC_K", 8))
    s = int(_env_num("COS_SYNC_STALENESS", 8))
    if k < 1 or s < 1:
        raise ValueError("COS_SYNC_K / COS_SYNC_STALENESS must be >= 1")
    wire = os.environ.get("COS_SYNC_WIRE_DTYPE",
                          "float32").strip().lower()
    if wire not in WIRE_DTYPES:
        raise ValueError(
            f"COS_SYNC_WIRE_DTYPE={wire!r}: expected one of "
            f"{'|'.join(WIRE_DTYPES)}")
    return SyncPolicy(
        mode=mode, k=k, staleness=s,
        alpha=float(_env_num("COS_SYNC_ALPHA", 0.0)),
        round_timeout_s=_env_num("COS_SYNC_ROUND_TIMEOUT_S", 30.0),
        heartbeat_timeout_s=_env_num("COS_SYNC_HEARTBEAT_TIMEOUT_S",
                                     10.0),
        wire_dtype=wire,
        store=os.environ.get("COS_SYNC_STORE", "").strip())


# ---------------------------------------------------------------------------
# wire encode/decode: the published payload's dtype (averaging stays f32)
def _encode_wire(flat: HostFlat, wire: str) -> Dict[str, np.ndarray]:
    if wire == "bfloat16":
        import ml_dtypes
        # npz has no bf16: ship the raw 16-bit pattern, tagged
        out = {k: np.asarray(v, ml_dtypes.bfloat16).view(np.uint16)
               for k, v in flat.items()}
        out["__wire__"] = np.asarray(1, np.int32)
        return out
    return {k: np.asarray(v, np.float32) for k, v in flat.items()}


def _decode_wire(npz) -> HostFlat:
    if "__wire__" in npz:
        import ml_dtypes
        return {k: np.asarray(npz[k].view(ml_dtypes.bfloat16),
                              np.float32)
                for k in npz.files if k != "__wire__"}
    return {k: np.asarray(npz[k], np.float32) for k in npz.files}


def average_flats(flats: List[HostFlat]) -> HostFlat:
    """Equal-weight f32 mean over contributions (SparkNet's periodic
    model average).  Every contribution must carry the same keys — a
    mismatch means two ranks compiled different nets, which is a
    config error worth failing loudly on."""
    if not flats:
        raise ValueError("average_flats: no contributions")
    keys = set(flats[0])
    for f in flats[1:]:
        if set(f) != keys:
            raise ValueError("param-average key mismatch between "
                             "contributions (different nets?)")
    n = float(len(flats))
    return {k: sum(np.asarray(f[k], np.float32) for f in flats) / n
            for k in keys}


# ---------------------------------------------------------------------------
class ParamStore:
    """Shared-filesystem parameter store: heartbeats, per-round
    contributions, and a versioned global (averaged) state.

    All writes are atomic (tmp + os.replace) so readers only ever see
    complete files; all I/O runs under a short retry loop that absorbs
    transient failures — including the ones `COS_FAULT_FLAKY_STORAGE`
    injects.  The root lives in the run's output directory
    (`<output>/.sync`), the same shared-storage assumption the
    supervisor's snapshot resume makes (NFS on pods; object stores
    without atomic rename are out of scope for the store)."""

    RETRIES = 8
    RETRY_BASE_S = 0.005
    LOCK_STALE_S = 10.0

    def __init__(self, root: str, rank: int, policy: SyncPolicy,
                 chaos=None):
        self.root = root
        self.rank = int(rank)
        self.policy = policy
        self.chaos = chaos          # ChaosInjector or None
        os.makedirs(root, exist_ok=True)
        self._last_hb = 0.0

    # -- I/O core ------------------------------------------------------
    def _retry(self, fn: Callable, what: str):
        import zipfile
        last = None
        for attempt in range(self.RETRIES):
            try:
                if self.chaos is not None:
                    self.chaos.storage_fault()
                return fn()
            except (OSError, ValueError, json.JSONDecodeError,
                    KeyError, EOFError,
                    zipfile.BadZipFile) as e:  # noqa: PERF203
                last = e
                time.sleep(self.RETRY_BASE_S * (2 ** attempt))
        raise OSError(f"ParamStore: {what} failed after "
                      f"{self.RETRIES} attempts") from last

    def _write_atomic(self, name: str, writer: Callable[[str], None]):
        path = os.path.join(self.root, name)
        tmp = f"{path}.tmp.{os.getpid()}"

        def _do():
            writer(tmp)
            os.replace(tmp, path)

        try:
            self._retry(_do, f"write {name}")
        finally:
            if os.path.exists(tmp):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass

    def _write_json(self, name: str, obj: dict):
        def w(tmp):
            with open(tmp, "w") as f:
                json.dump(obj, f)
        self._write_atomic(name, w)

    def _read_json(self, name: str) -> Optional[dict]:
        path = os.path.join(self.root, name)
        if not os.path.exists(path):
            return None

        def r():
            with open(path) as f:
                return json.load(f)
        return self._retry(r, f"read {name}")

    def _write_npz(self, name: str, flat: HostFlat):
        payload = _encode_wire(flat, self.policy.wire_dtype)

        def w(tmp):
            with open(tmp, "wb") as f:
                np.savez(f, **payload)
        self._write_atomic(name, w)

    def _read_npz(self, name: str) -> HostFlat:
        path = os.path.join(self.root, name)

        def r():
            with np.load(path) as npz:
                return _decode_wire(npz)
        return self._retry(r, f"read {name}")

    # -- transport seams (overridden by HttpParamStore) ----------------
    def _list_names(self) -> List[str]:
        """Every object name in the store (the directory listing)."""
        return os.listdir(self.root)

    def _delete(self, name: str):
        os.unlink(os.path.join(self.root, name))

    # -- heartbeats / membership ---------------------------------------
    def heartbeat(self, it: int, *, done: bool = False,
                  force: bool = False):
        """Publish liveness + progress.  Rate-limited off the hot path
        (the step loop calls this every dispatch); exchange boundaries
        force a write so membership sees boundary-accurate progress."""
        now = time.time()
        min_gap = min(1.0, self.policy.heartbeat_timeout_s / 4.0)
        if not force and not done and now - self._last_hb < min_gap:
            return
        self._last_hb = now
        self._write_json(f"hb_rank{self.rank}.json",
                         {"rank": self.rank, "iter": int(it),
                          "ts": now, "done": bool(done)})

    def members(self) -> Dict[int, dict]:
        """Every rank ever seen: rank -> {iter, ts, done, live}."""
        now = time.time()
        out: Dict[int, dict] = {}
        for name in self._list_names():
            if not (name.startswith("hb_rank")
                    and name.endswith(".json")):
                continue
            hb = self._read_json(name)
            if hb is None:
                continue
            hb["live"] = (not hb.get("done")
                          and now - hb["ts"]
                          <= self.policy.heartbeat_timeout_s)
            out[int(hb["rank"])] = hb
        return out

    def live_ranks(self) -> Dict[int, int]:
        """rank -> last-heartbeat iter, live (fresh, not done) only."""
        return {r: hb["iter"] for r, hb in self.members().items()
                if hb["live"]}

    # -- local_sgd rounds ----------------------------------------------
    def _round_name(self, rnd: int, rank: int) -> str:
        return f"round_{rnd:08d}_rank{rank}.npz"

    def publish_round(self, rnd: int, flat: HostFlat):
        self._write_npz(self._round_name(rnd, self.rank), flat)

    def round_ranks(self, rnd: int) -> List[int]:
        prefix = f"round_{rnd:08d}_rank"
        out = []
        for name in self._list_names():
            if name.startswith(prefix) and name.endswith(".npz"):
                out.append(int(name[len(prefix):-len(".npz")]))
        return sorted(out)

    def read_round(self, rnd: int) -> Dict[int, HostFlat]:
        out = {}
        for r in self.round_ranks(rnd):
            try:
                out[r] = self._read_npz(self._round_name(rnd, r))
            except OSError:
                # a contribution that cannot be read after retries is
                # treated like a rank that missed the round
                continue
        return out

    # -- global (averaged) state ---------------------------------------
    def publish_global(self, version: int, it: int,
                       members: List[int], flat: HostFlat):
        fname = f"global_v{version:08d}.npz"
        self._write_npz(fname, flat)
        self._write_json("global.json",
                         {"version": int(version), "iter": int(it),
                          "members": sorted(int(m) for m in members),
                          "file": fname, "ts": time.time()})
        self._gc(version)

    def latest_global_meta(self) -> Optional[dict]:
        return self._read_json("global.json")

    def load_global(self) -> Optional[dict]:
        """Latest averaged state: meta dict + 'params' HostFlat."""
        meta = self.latest_global_meta()
        if meta is None:
            return None
        meta = dict(meta)
        meta["params"] = self._read_npz(meta["file"])
        return meta

    def _gc(self, version: int):
        """Best-effort cleanup: keep the last two globals and the last
        three rounds' contributions (a detached straggler may still be
        reading slightly-old files; anything older is garbage)."""
        for name in self._list_names():
            try:
                if name.startswith("global_v") and name.endswith(".npz"):
                    v = int(name[len("global_v"):-len(".npz")])
                    if v <= version - 2:
                        self._delete(name)
                elif name.startswith("round_"):
                    rnd = int(name[len("round_"):len("round_") + 8])
                    if rnd <= version - 3:
                        self._delete(name)
            except (OSError, ValueError):
                continue

    # -- async merge lock ----------------------------------------------
    def lock_global(self) -> bool:
        """Try-acquire the merge lock (O_EXCL create).  A lock older
        than LOCK_STALE_S is broken — its holder died mid-merge.  The
        break itself is a RENAME, not an unlink: exactly one contender
        wins the rename (the rest get ENOENT and simply retry), so two
        waiters can never both "break" the same lock and overlap their
        merges; the winner still re-acquires through O_EXCL on its next
        attempt rather than inheriting the lock."""
        path = os.path.join(self.root, "global.lock")
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.write(fd, json.dumps(
                {"rank": self.rank, "ts": time.time()}).encode())
            os.close(fd)
            return True
        except FileExistsError:
            try:
                age = time.time() - os.path.getmtime(path)
                if age > self.LOCK_STALE_S:
                    broken = f"{path}.broken.{os.getpid()}"
                    os.rename(path, broken)
                    os.unlink(broken)
            except OSError:
                pass
            return False

    def unlock_global(self):
        try:
            os.unlink(os.path.join(self.root, "global.lock"))
        except OSError:
            pass


class HttpParamStore(ParamStore):
    """ParamStore over a NodeAgent's blob API — the no-shared-
    filesystem transport (COS_SYNC_STORE=http://agent:port).  Only the
    I/O primitives change: every read/write/list/delete becomes an
    HTTP round-trip to /v1/blob*, the merge lock becomes POST
    /v1/lock (the agent runs the same O_EXCL + stale-break-by-rename
    protocol server-side), and everything above — heartbeats, round
    membership, versioned globals, GC — is inherited untouched.  The
    retry loop (and with it COS_FAULT_FLAKY_STORAGE injection) stays
    CLIENT-side in the inherited `_retry`, so flaky-storage semantics
    are identical to the shared-filesystem path by construction."""

    def __init__(self, url: str, rank: int, policy: SyncPolicy,
                 chaos=None):
        # deliberately no super().__init__: the root is a URL, there
        # is no local directory to create
        self.root = url.rstrip("/")
        self.rank = int(rank)
        self.policy = policy
        self.chaos = chaos
        self._last_hb = 0.0

    # -- HTTP primitives -----------------------------------------------
    def _call(self, path: str, *, data=None, method=None, raw=False):
        import http.client
        from ..tools.nodeagent import agent_call
        try:
            return agent_call(self.root, path, data=data,
                              method=method, raw=raw, timeout=10.0)
        except http.client.HTTPException as e:
            # normalize mid-response deaths to the OSError the
            # inherited retry loop (and every caller) already absorbs
            raise OSError(f"agent transport: {e}") from e

    def _put_bytes(self, name: str, payload: bytes):
        self._call(f"/v1/blob/{name}", data=payload, method="PUT")

    def _get_bytes(self, name: str) -> Optional[bytes]:
        return self._call(f"/v1/blob/{name}", raw=True)

    # -- transport seams -----------------------------------------------
    def _list_names(self) -> List[str]:
        doc = self._retry(lambda: self._call("/v1/blobs"),
                          "list blobs")
        return list((doc or {}).get("names") or [])

    def _delete(self, name: str):
        self._call(f"/v1/blob/{name}", method="DELETE")

    def _write_json(self, name: str, obj: dict):
        payload = json.dumps(obj).encode()
        self._retry(lambda: self._put_bytes(name, payload),
                    f"write {name}")

    def _read_json(self, name: str) -> Optional[dict]:
        def r():
            data = self._get_bytes(name)
            return None if data is None else json.loads(data)
        return self._retry(r, f"read {name}")

    def _write_npz(self, name: str, flat: HostFlat):
        payload = _encode_wire(flat, self.policy.wire_dtype)
        buf = io.BytesIO()
        np.savez(buf, **payload)
        raw = buf.getvalue()
        self._retry(lambda: self._put_bytes(name, raw),
                    f"write {name}")

    def _read_npz(self, name: str) -> HostFlat:
        def r():
            data = self._get_bytes(name)
            if data is None:
                # same shape as the fs path reading a missing file:
                # an OSError the retry loop (and read_round) absorbs
                raise FileNotFoundError(f"{self.root}/{name}")
            with np.load(io.BytesIO(data)) as npz:
                return _decode_wire(npz)
        return self._retry(r, f"read {name}")

    # -- async merge lock ----------------------------------------------
    def lock_global(self) -> bool:
        """Same contract as the fs lock: try-acquire, never block.  The
        stale-break runs server-side (the agent renames a lock older
        than `stale_s` away); an unreachable agent reads as 'lock
        busy' — the caller's bounded retry loop already handles both."""
        try:
            doc = self._call("/v1/lock",
                             data={"name": "global.lock",
                                   "owner": self.rank,
                                   "stale_s": self.LOCK_STALE_S})
        except OSError:
            return False
        return bool((doc or {}).get("acquired"))

    def unlock_global(self):
        try:
            self._call("/v1/unlock", data={"name": "global.lock"})
        except OSError:
            pass


# ---------------------------------------------------------------------------
class _SyncBase:
    """Common machinery for the relaxed modes.  The trainer's step loop
    calls `maybe_exchange(it, get, put)` after every dispatch; `get`
    returns the host (flat f32) params, `put` places a flat dict back
    onto the devices.  The call returns the rank's iteration — USUALLY
    `it` unchanged, but a detached straggler or a rejoiner is fast-
    forwarded to the pack's clock when it adopts the pack average (the
    re-admission: from then on its boundaries align with the pack's
    and it contributes again).  At startup `adopt_latest()` offers the
    newest averaged state for the elastic rejoin path."""

    def __init__(self, policy: SyncPolicy, store: ParamStore,
                 rank: int, chaos=None):
        self.policy = policy
        self.store = store
        self.rank = int(rank)
        self.chaos = chaos
        self._last_exchange = 0
        self.counts = {"exchanges": 0, "skipped": 0, "adopted": 0,
                       "timeouts": 0}
        self.max_gap = 0

    # -- rejoin --------------------------------------------------------
    def adopt_latest(self, after_iter: int = -1) -> Optional[dict]:
        """Newest averaged state from the store STRICTLY ahead of
        `after_iter`, for a (re)joining rank: {'iter', 'version',
        'params'} or None.  The caller jumps its iteration to 'iter'
        so it re-admits at the next round; the adoption is only
        counted when a usable state is actually returned."""
        meta = self.store.latest_global_meta()
        if meta is None or meta["iter"] <= after_iter:
            return None
        g = self.store.load_global()
        if g is None or g["iter"] <= after_iter:
            return None
        self.counts["adopted"] += 1
        return g

    def on_start(self, it: int):
        self._last_exchange = it
        self.store.heartbeat(it, force=True)

    def finalize(self, it: int):
        """Mark this rank done so peers' soft barriers stop expecting
        it immediately instead of after a heartbeat timeout."""
        try:
            self.store.heartbeat(it, done=True, force=True)
        except OSError:
            pass

    def info(self) -> dict:
        out = dict(self.policy.describe())
        out.update(self.counts)
        out["max_gap"] = self.max_gap
        if self.chaos is not None:
            out.update(self.chaos.injected)
        return out

    # -- shared helpers ------------------------------------------------
    def _at_boundary(self, it: int, interval: int) -> bool:
        return (it > 0 and it % interval == 0
                and it != self._last_exchange)

    def _adopt(self, put: Callable[[HostFlat], None]) -> Optional[int]:
        """Adopt the pack's averaged state and jump to its clock."""
        g = self.store.load_global()
        if g is None:
            return None
        put(g["params"])
        self.counts["adopted"] += 1
        self._last_exchange = int(g["iter"])
        self.store.heartbeat(self._last_exchange, force=True)
        return self._last_exchange

    def maybe_exchange(self, it: int,
                       get: Callable[[], HostFlat],
                       put: Callable[[HostFlat], None]) -> int:
        raise NotImplementedError


class LocalSGDSync(_SyncBase):
    """SparkNet-style periodic model averaging with a SOFT round
    barrier: wait (up to round_timeout_s) only for live, attached
    ranks within one round of this boundary.  Detachment is STICKY —
    a rank that times out a round is not waited for again until its
    contribution actually shows up in a current round (otherwise a
    persistent straggler sitting exactly one round behind would tax
    the pack a full slow-round EVERY round).  A detached straggler
    that reaches a boundary and finds the pack's global state ahead
    drops its stale round, adopts the average, and jumps to the
    pack's clock — the same re-admission path a supervisor-relaunched
    rank takes; if it then keeps pace, its next contribution lands in
    a live round and re-attaches it."""

    POLL_S = 0.05

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._detached: set = set()
        self._last_boundary_t: Optional[float] = None

    def maybe_exchange(self, it, get, put) -> int:
        k = self.policy.k
        if not self._at_boundary(it, k):
            self.store.heartbeat(it)
            return it
        prev, self._last_exchange = self._last_exchange, it
        # adaptive patience: a healthy peer arrives within about one
        # of OUR round wall-times, so don't wait the full configured
        # timeout for one that doesn't (round 1 has no measurement —
        # and carries the jit-compile skew — so it gets the full
        # timeout)
        now_t = time.monotonic()
        own_round = (now_t - self._last_boundary_t
                     if self._last_boundary_t is not None else None)
        self._last_boundary_t = now_t
        patience = self.policy.round_timeout_s
        if own_round is not None:
            patience = min(patience,
                           max(4 * self.POLL_S, 1.5 * own_round))
        self.store.heartbeat(it, force=True)
        self.max_gap = max(self.max_gap, it - prev)

        # detached / late: the pack already averaged past this point —
        # our K steps since the last average are stale against a pack
        # that moved on; adopt + fast-forward (re-admission)
        meta = self.store.latest_global_meta()
        if meta is not None and meta["iter"] > it:
            new_it = self._adopt(put)
            if new_it is not None:
                return new_it

        if self.chaos is not None and self.chaos.exchange_fault():
            # transient exchange fault: local_sgd SKIPS the round —
            # round semantics tolerate a missing contribution, and the
            # next boundary resynchronizes us
            self.counts["skipped"] += 1
            return it

        rnd = it // k
        flat = get()
        self.store.publish_round(rnd, flat)
        deadline = time.monotonic() + patience
        while True:
            have = set(self.store.round_ranks(rnd))
            # a detached rank whose contribution shows up in THIS
            # round is keeping pace again: re-attach it
            self._detached -= have
            # the PACK: live, attached ranks within one round of this
            # boundary (a dead rank's heartbeat goes stale and drops
            # out; a straggler >1 round behind never qualifies)
            expected = ({self.rank} | {
                r for r, hb_it in self.store.live_ranks().items()
                if hb_it >= it - k}) - self._detached
            if expected <= have:
                break
            if time.monotonic() >= deadline:
                # whoever kept the pack waiting past the timeout is
                # detached until they demonstrably keep pace again
                self._detached |= expected - have - {self.rank}
                self.counts["timeouts"] += 1
                break
            time.sleep(self.POLL_S)

        conts = self.store.read_round(rnd)
        conts.setdefault(self.rank, flat)
        avg = average_flats(list(conts.values()))
        put(avg)
        # lowest contributing rank publishes the round average as the
        # new global — the adoption point for rejoiners and the
        # averaged-state resume
        if self.rank == min(conts):
            self.store.publish_global(rnd, it, sorted(conts), avg)
        self.counts["exchanges"] += 1
        return it

    def info(self) -> dict:
        out = super().info()
        out["detached_now"] = sorted(self._detached)
        return out


class AsyncSync(_SyncBase):
    """DeepSpark-style bounded staleness without any barrier: at least
    every `staleness` local steps the rank merges its params into the
    versioned global state (new = (1-a)·global + a·local, a = 1/live
    by default, down-weighted by how stale the contribution is) under
    a short file lock.  The bound is a promise, so a merge that cannot
    land is RETRIED — the rank waits on the sync, never on a
    straggler; a rank more than 4 bounds behind re-admits itself by
    adopting the global state at the pack's clock."""

    # the retry schedule must OUTLAST the lock's stale window (a dead
    # holder's lock is only breakable after LOCK_STALE_S): ~17s of
    # capped backoff vs the 10s window
    MERGE_RETRIES = 16
    RETRY_BASE_S = 0.05
    RETRY_CAP_S = 2.0

    def _merge_once(self, it: int, flat: HostFlat) -> HostFlat:
        if self.chaos is not None and self.chaos.exchange_fault():
            raise OSError("injected flaky-exchange fault")
        if not self.store.lock_global():
            raise OSError("global merge lock busy")
        try:
            g = self.store.load_global()
            live = self.store.live_ranks()
            if g is None:
                new, version, members = flat, 1, [self.rank]
            else:
                a = self.policy.alpha or 1.0 / max(1, len(live) or 1)
                # staleness-aware weight: a contribution computed on
                # params `lag` steps behind the global clock merges
                # with proportionally less authority (DeepSpark's
                # staleness-dependent update)
                lag = max(0, g["iter"] - it)
                a = a / (1.0 + lag / float(self.policy.staleness))
                gp = g["params"]
                new = {k2: (1.0 - a) * gp[k2] + a * np.asarray(
                    v, np.float32) for k2, v in flat.items()}
                version = g["version"] + 1
                members = sorted(set(g.get("members", []))
                                 | {self.rank})
            self.store.publish_global(version, max(
                it, g["iter"] if g else 0), members, new)
            return new
        finally:
            self.store.unlock_global()

    def maybe_exchange(self, it, get, put) -> int:
        s = self.policy.staleness
        if not self._at_boundary(it, s):
            self.store.heartbeat(it)
            return it
        prev, self._last_exchange = self._last_exchange, it
        self.store.heartbeat(it, force=True)
        self.max_gap = max(self.max_gap, it - prev)

        # hopelessly stale (over 4 staleness bounds behind the global
        # clock): merging would only drag the average back — re-admit
        # at the pack's clock instead
        meta = self.store.latest_global_meta()
        if meta is not None and meta["iter"] - it > 4 * s:
            new_it = self._adopt(put)
            if new_it is not None:
                return new_it

        flat = get()
        last = None
        for attempt in range(self.MERGE_RETRIES):
            try:
                new = self._merge_once(it, flat)
                put(new)
                self.counts["exchanges"] += 1
                return it
            except OSError as e:    # noqa: PERF203 — retry loop
                last = e
                time.sleep(min(self.RETRY_CAP_S,
                               self.RETRY_BASE_S * (1.5 ** attempt)))
        raise OSError(
            "async sync: global merge failed after "
            f"{self.MERGE_RETRIES} attempts — the staleness bound "
            f"cannot be honored at iter {it}") from last


def make_sync(policy: SyncPolicy, output_dir: str, rank: int,
              chaos=None, store_root: Optional[str] = None
              ) -> Optional[_SyncBase]:
    """Sync object for a trainer process, or None for lockstep (the
    default stays byte-identical by never constructing anything).  The
    store root resolves explicit arg > COS_SYNC_STORE (policy.store) >
    the shared-filesystem default; an http(s):// root selects the
    NodeAgent blob transport."""
    if not policy.elastic:
        return None
    root = (store_root or policy.store
            or os.path.join(output_dir, ".sync"))
    if root.startswith(("http://", "https://")):
        store: ParamStore = HttpParamStore(root, rank, policy,
                                           chaos=chaos)
    else:
        store = ParamStore(root, rank, policy, chaos=chaos)
    cls = LocalSGDSync if policy.mode == "local_sgd" else AsyncSync
    return cls(policy, store, rank, chaos=chaos)
