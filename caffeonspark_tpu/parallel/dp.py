"""Data-parallel (+ tensor-parallel) execution of a Solver step over a
device mesh.

This replaces the whole L1 sync stack of the reference — `P2PSync` tree
reduce, `SocketSync`/`RDMASync` sharded parameter-server exchange
(`socket_sync.cpp`, SURVEY §2.6), and the `1/solver_count` gradient
scaling (`parallel_cpu.cpp:120-122`) — with GSPMD: inputs are sharded on
the `dp` axis, parameters are replicated (or `tp`-sharded), and XLA
inserts the gradient all-reduce (a psum over ICI) automatically because
the loss is a global mean over the sharded batch.  Semantically the step
IS the single-device step — same loss, same update — executed across the
slice; the barrier of `CaffeNet::sync` is implicit in the collective.

Tensor parallelism: `tp_param_specs` shards large InnerProduct / Embed
weights over the `tp` axis (Megatron-style column split on num_output).
XLA partitions the matmuls and inserts all-gathers/reduce-scatters where
layouts demand; convs stay replicated (batch dominates for the CNN zoo).

ZeRO-1 (`COS_ZERO=1` or ParallelSolver(zero_dp=True)):
`zero_state_specs` shards the OPTIMIZER STATE over `dp` while params
stay replicated — GSPMD turns the elementwise update into a per-shard
update + param all-gather, cutting per-chip optimizer HBM (capacity
AND the state read+write traffic `scripts/roofline.py` flags as the
fc6/fc7 bottleneck) by ~dp.  Composes with `COS_STATE_DTYPE=bfloat16`.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..net import Net
from ..solver import OptState, Solver
from .mesh import (MeshLayout, TP_MIN_FEATURES, replicated,  # noqa: F401
                   tp_param_specs)

# tp_param_specs/TP_MIN_FEATURES moved to mesh.py (MeshLayout is the
# one spec-construction path, shared with serving); re-exported here
# for the historical import site.

Array = jax.Array


ZERO_MIN_NUMEL = 16384  # shard only state blobs big enough to matter


def zero_state_specs(param_specs: Dict[str, Dict[str, P]],
                     shapes: Dict[str, Dict[str, tuple]],
                     dp: int, *, min_numel: int = ZERO_MIN_NUMEL
                     ) -> Dict[str, Dict[str, P]]:
    """ZeRO-1-style optimizer-STATE specs: for each blob big enough to
    matter, add 'dp' on the LARGEST unsharded dim divisible by the dp
    size — largest so the shards balance (fc-style (4096, 25088) blobs
    shard the 25088 axis; picking the first divisible dim would cut
    the small axis and leave 6x more elements per shard boundary) —
    while params stay replicated (only the momentum / second-moment
    history shards).  Under GSPMD the elementwise update then runs
    per-shard and XLA all-gathers the updated params, i.e. the ZeRO-1
    partition-update-allgather pattern falls out of the sharding
    annotations — no hand-written collectives (the TPU-native analog
    of DeepSpeed's stage-1 partitioning).  Per-chip optimizer HBM (and
    the state read+write traffic the roofline flags on fc6/fc7) drops
    by ~dp."""
    out: Dict[str, Dict[str, P]] = {}
    for ln, blobs in param_specs.items():
        out[ln] = {}
        for bn, spec in blobs.items():
            shape = shapes[ln][bn]
            numel = int(np.prod(shape)) if shape else 0
            new = spec
            if dp > 1 and numel >= min_numel:
                used = set(spec)
                if "dp" not in used:
                    axes = list(spec) + [None] * (len(shape) - len(spec))
                    best = None
                    for i, (ax, dim) in enumerate(zip(axes, shape)):
                        if ax is None and dim % dp == 0 and (
                                best is None or dim > shape[best]):
                            best = i
                    if best is not None:
                        axes[best] = "dp"
                        new = P(*axes)
            out[ln][bn] = new
    return out


class ParallelSolver:
    """Wraps a Solver's train/eval step for mesh execution."""

    def __init__(self, solver: Solver, mesh: Mesh, *,
                 tensor_parallel: bool = True,
                 zero_dp: Optional[bool] = None):
        import os
        self.solver = solver
        self.mesh = mesh
        # spec construction is shared with serving (mesh.MeshLayout):
        # same tp/ep layouts, same divisibility guard — the training
        # step and the serving forward can never disagree on where a
        # blob's shards live
        self.layout = MeshLayout(solver.train_net, mesh,
                                 tensor_parallel=tensor_parallel)
        self.tp_on = self.layout.tp_on
        if zero_dp is None:
            zero_dp = os.environ.get("COS_ZERO") == "1"
        self.zero_on = bool(zero_dp) and mesh.shape.get("dp", 1) > 1
        self.param_specs = self.layout.param_specs
        shapes = self.layout.shapes
        self.param_sharding = self.layout.param_sharding
        if self.zero_on:
            self.state_specs = zero_state_specs(
                self.param_specs, shapes, mesh.shape.get("dp", 1))
            self.state_sharding = {
                ln: {bn: NamedSharding(mesh, spec)
                     for bn, spec in blobs.items()}
                for ln, blobs in self.state_specs.items()}
        else:
            self.state_specs = self.param_specs
            self.state_sharding = self.param_sharding
        self.repl = replicated(mesh)
        # explicit gradient exchange (gradsync.py): the mesh resolves
        # COS_GRAD_SYNC=auto and arms the collective constraints; blobs
        # sharded over tp/ep keep GSPMD's handling (their grads are not
        # replicated — bucketing them would force an all-gather).  Must
        # happen before any step is traced (steps build lazily below).
        gs = getattr(solver, "grad_sync", None)
        if gs is not None:
            sharded = frozenset(
                (ln, bn) for ln, blobs in self.param_specs.items()
                for bn, spec in blobs.items()
                if any(ax is not None for ax in spec))
            gs.bind_mesh(mesh, skip_blobs=sharded)
        self._step = None
        self._step_many: Dict[int, object] = {}
        self._eval = None

    # ------------------------------------------------------------------
    def shard_params(self, params) -> Dict:
        return self.layout.place_params(params)

    # -- host-side param exchange (sync modes) -------------------------
    def host_params(self, params) -> Dict[str, "np.ndarray"]:
        """Flat host copy of the live params, for the elastic sync
        modes' host-side exchange (parallel/syncmode.py) — the local
        mesh's layout is erased (device_get densifies local shards),
        so ranks with different local meshes can still average."""
        from ..checkpoint import flatten_host_params
        return flatten_host_params(params)

    def place_host_params(self, flat: Dict[str, "np.ndarray"],
                          like) -> Dict:
        """Inverse of host_params: place a flat (f32) host dict back
        onto the mesh with each blob cast to the dtype of the current
        params `like` (the store's averaging math runs f32 regardless
        of the net dtype)."""
        from ..checkpoint import unflatten_host_params
        host = unflatten_host_params(flat)
        cast = {ln: {bn: np.asarray(arr, like[ln][bn].dtype)
                     for bn, arr in bl.items()}
                for ln, bl in host.items()}
        return self.shard_params(cast)

    def set_iter(self, st: OptState, it: int) -> OptState:
        """Rebuild the opt-state iteration counter (elastic re-
        admission fast-forwards a rank to the pack's clock; the LR
        schedule must follow)."""
        import jax.numpy as jnp
        return OptState(
            iter=jax.device_put(jnp.asarray(int(it), jnp.int32),
                                self.repl),
            history=st.history, history2=st.history2)

    def shard_opt_state(self, st: OptState) -> OptState:
        hist = {ln: {bn: jax.device_put(arr, self.state_sharding[ln][bn])
                     for bn, arr in blobs.items()}
                for ln, blobs in st.history.items()}
        hist2 = {ln: {bn: jax.device_put(arr, self.state_sharding[ln][bn])
                      for bn, arr in blobs.items()}
                 for ln, blobs in st.history2.items()}
        return OptState(iter=jax.device_put(st.iter, self.repl),
                        history=hist, history2=hist2)

    def _input_specs(self, net: Optional[Net] = None) -> Dict[str, P]:
        """Per-input PartitionSpec — shared construction (MeshLayout):
        batch over dp, time-major tops additionally over sp."""
        return self.layout.input_specs(net)

    def input_shardings(self, net: Optional[Net] = None) -> Dict[str, NamedSharding]:
        return self.layout.input_shardings(net)

    def chunk_input_shardings(self, net: Optional[Net] = None
                              ) -> Dict[str, NamedSharding]:
        """Shardings for the stacked (K, batch…) input blocks of the
        fused multi-step path: the leading chunk axis is scanned over
        on every device (unsharded), each per-step slice keeps its
        input_shardings spec."""
        return {name: NamedSharding(self.mesh, P(*((None,) + tuple(spec))))
                for name, spec in self._input_specs(net).items()}

    def shard_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, Array]:
        sh = self.input_shardings()
        return {k: jax.device_put(v, sh[k]) for k, v in batch.items()}

    # ------------------------------------------------------------------
    def init(self) -> Tuple[Dict, OptState]:
        params, st = self.solver.init()
        params = self.shard_params(params)
        return params, self.shard_opt_state(st)

    def train_step(self):
        """Jitted SPMD step: donated params/opt, dp-sharded inputs."""
        if self._step is None:
            base = self._install_flash_mesh(self.solver.train_step_fn())
            in_sh = (
                self.param_sharding,
                OptState(iter=self.repl,
                         history=self.state_sharding,
                         history2=self.state_sharding),
                self.input_shardings(),
                self.repl,
            )
            out_sh = (in_sh[0], in_sh[1], None)
            self._step = jax.jit(base, donate_argnums=(0, 1),
                                 in_shardings=in_sh,
                                 out_shardings=out_sh)
        return self._step

    def train_step_many(self, k: int):
        """Jitted fused K-step SPMD program (Solver.build_train_step_many
        under the mesh): donated params/opt, chunk-stacked dp-sharded
        inputs, per-step rng folded in on-device.  Composes with TP and
        ZeRO-1 exactly like the single step — the scan body IS that
        step, so GSPMD inserts the same collectives per iteration."""
        if k not in self._step_many:
            base = self._install_flash_mesh(
                self.solver.build_train_step_many(k))
            in_sh = (
                self.param_sharding,
                OptState(iter=self.repl,
                         history=self.state_sharding,
                         history2=self.state_sharding),
                self.chunk_input_shardings(),
            )
            out_sh = (in_sh[0], in_sh[1], None)
            self._step_many[k] = jax.jit(base, donate_argnums=(0, 1),
                                         in_shardings=in_sh,
                                         out_shardings=out_sh)
        return self._step_many[k]

    def _install_flash_mesh(self, fn):
        """Route pallas attention dispatches through shard_map on
        meshes (MeshLayout.install_flash — shared with the serving
        forward); when the mesh also shards TIME (sp), the shard_map
        body is the differentiable fused ring."""
        return self.layout.install_flash(fn)

    def eval_step(self):
        """Jitted validation forward — built by the SAME BlobForward
        the serving and batch-extract paths use (serving/forward.py),
        against this solver's layout: one forward-construction path."""
        if self._eval is None:
            from ..serving.forward import BlobForward
            net = self.solver.test_net
            assert net is not None, "no TEST-phase net in this config"
            self._eval = BlobForward(net, layout=self.layout)(
                tuple(net.output_blobs))
        return self._eval

    @property
    def num_dp_ranks(self) -> int:
        return self.mesh.shape.get("dp", 1)

    def global_batch(self, per_device_batch: int) -> int:
        """README: 'Batch sizes specified in prototxt files are per
        device' — the global batch scales with dp."""
        return per_device_batch * self.num_dp_ranks
