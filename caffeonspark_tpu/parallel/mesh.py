"""Device mesh construction + multi-host bootstrap.

TPU-native replacement for the reference's entire connection machinery:
RDMA/socket server address exchange via Spark collect
(`CaffeOnSpark.scala:113-142`), `SocketChannel::Connect` retries
(`socket.cpp:242-281`), and TCP `MiniCluster::AllGather` rank assignment
(`mini_cluster.cpp:22-66`) all collapse into `jax.distributed.initialize`
(coordinator address = the "server" flag) plus a named `Mesh`.  The
cluster barrier (`CaffeNet::sync`, `socket_sync.cpp:156-183`) is implicit
in every SPMD collective.

Mesh axes:
  dp — data parallel (batch sharding, gradient pmean)
  tp — tensor parallel (weight sharding on large InnerProducts)
  sp — sequence parallel (ring attention / long-context)
  pp — pipeline parallel (stage-partitioned nets)
  ep — expert parallel (MixtureOfExperts expert-dim sharding)
Axes of size 1 cost nothing; lay dp innermost-last so its collectives
ride ICI neighbors first.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("pp", "ep", "sp", "tp", "dp")


def distributed_init(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap (the address-exchange / rank-assignment
    analog).  No-op for single-process runs."""
    if coordinator is None:
        return
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)


def build_mesh(*, dp: Optional[int] = None, tp: int = 1, sp: int = 1,
               pp: int = 1, ep: int = 1, devices=None) -> Mesh:
    """Mesh over all devices with named axes (pp, ep, sp, tp, dp); dp is
    inferred as the remainder when unset."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    fixed = tp * sp * pp * ep
    if n % fixed != 0:
        raise ValueError(
            f"{n} devices not divisible by tp*sp*pp*ep={fixed}")
    if dp is None:
        dp = n // fixed
    if dp * fixed != n:
        raise ValueError(f"dp*tp*sp*pp*ep={dp * fixed} != {n} devices")
    arr = np.asarray(devices).reshape(pp, ep, sp, tp, dp)
    return Mesh(arr, AXES)


def data_sharding(mesh: Mesh, batch_axis: int = 0) -> NamedSharding:
    """Shard the batch dimension across dp AND sp together — for pure
    data parallelism on a mesh that also carries an sp axis, both axes
    consume the global batch so no devices idle."""
    spec = [None] * (batch_axis + 1)
    spec[batch_axis] = ("dp", "sp") if mesh.shape.get("sp", 1) > 1 \
        else "dp"
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def dp_data_rank(mesh: Mesh) -> tuple:
    """(data_rank, data_num_ranks) for THIS process: which shard of
    the record stream it must feed.

    Derived from the mesh coordinates of the local devices, NOT the
    process rank — on a tp/sp-only mesh every process sits at dp
    index 0 and must feed IDENTICAL records (its model shard consumes
    the same replicated batch), while the process-rank sharding the
    cluster flags imply would feed each rank different data and
    silently train on inconsistent replicas.  Single-process meshes
    feed the whole stream (device_prefetch shards locally)."""
    if jax.process_count() <= 1:
        return 0, 1
    dp_total = mesh.shape.get("dp", 1)
    if dp_total <= 1:
        return 0, 1
    axes = list(mesh.axis_names)
    dp_axis = axes.index("dp")
    local_ids = {d.id for d in jax.local_devices()}
    rows = sorted({idx[dp_axis]
                   for idx in np.ndindex(mesh.devices.shape)
                   if mesh.devices[idx].id in local_ids})
    k = len(rows)
    if (k and rows == list(range(rows[0], rows[0] + k))
            and dp_total % k == 0 and rows[0] % k == 0):
        return rows[0] // k, dp_total // k
    # non-contiguous local dp rows (exotic device order): feed the
    # whole stream rather than misalign the local shard
    return 0, 1


def lockstep_steps(total_records: int, batch_per_step: int,
                   num_ranks: int) -> int:
    """The minPartSize equalization invariant
    (`CaffeOnSpark.scala:185-200`): every rank must execute the SAME
    number of steps or a collective deadlocks the slice.  Returns the
    per-epoch step count = floor(min records per rank / batch)."""
    per_rank = total_records // num_ranks
    return max(0, per_rank // batch_per_step)
