"""Device mesh construction + multi-host bootstrap.

TPU-native replacement for the reference's entire connection machinery:
RDMA/socket server address exchange via Spark collect
(`CaffeOnSpark.scala:113-142`), `SocketChannel::Connect` retries
(`socket.cpp:242-281`), and TCP `MiniCluster::AllGather` rank assignment
(`mini_cluster.cpp:22-66`) all collapse into `jax.distributed.initialize`
(coordinator address = the "server" flag) plus a named `Mesh`.  The
cluster barrier (`CaffeNet::sync`, `socket_sync.cpp:156-183`) is implicit
in every SPMD collective.

Mesh axes:
  dp — data parallel (batch sharding, gradient pmean)
  tp — tensor parallel (weight sharding on large InnerProducts)
  sp — sequence parallel (ring attention / long-context)
  pp — pipeline parallel (stage-partitioned nets)
  ep — expert parallel (MixtureOfExperts expert-dim sharding)
Axes of size 1 cost nothing; lay dp innermost-last so its collectives
ride ICI neighbors first.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("pp", "ep", "sp", "tp", "dp")


def parse_mesh_spec(spec: str) -> Dict[str, int]:
    """'dp[,tp[,sp[,ep]]]' → build_mesh kwargs; rejects extra dims
    instead of silently dropping them.  Any token may instead be a
    named 'axis=N' dim ('pp=4', 'tp=2,pp=2', '2,2,pp=2') — the only
    spelling for the pp axis, which has no positional slot.  Shared by
    the training CLI (-mesh) and the serving CLI (-serveMesh)."""
    names = ["dp", "tp", "sp", "ep"]
    out: Dict[str, int] = {}
    pos = 0
    for tok in spec.split(","):
        tok = tok.strip()
        if "=" in tok:
            name, _, val = tok.partition("=")
            name = name.strip()
            if name not in AXES:
                raise ValueError(
                    f"mesh spec {spec!r}: unknown axis {name!r} "
                    f"(axes: {','.join(AXES)})")
            dim = int(val)
        else:
            if pos >= len(names):
                raise ValueError(
                    f"mesh spec {spec!r} has more than {len(names)} "
                    f"positional dims ({','.join(names)})")
            name = names[pos]
            pos += 1
            dim = int(tok)
        if name in out:
            raise ValueError(
                f"mesh spec {spec!r}: axis {name!r} given twice")
        if dim < 1:
            raise ValueError(
                f"mesh spec {spec!r}: axis {name!r} must be >= 1, "
                f"got {dim}")
        out[name] = dim
    return out


def distributed_init(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap (the address-exchange / rank-assignment
    analog).  No-op for single-process runs.

    `coordinator` is normally `host:port`; the `agent://host:port`
    form instead asks the NodeAgent at that address for the rendezvous
    (GET /v1/coordinator) — the LEAD agent allocates one coordinator
    port and hands every rank the same answer, so a cross-host job
    needs no hand-picked port, only the lead agent's address."""
    if coordinator is None:
        return
    if coordinator.startswith("agent://"):
        from ..tools.nodeagent import resolve_coordinator
        coordinator = resolve_coordinator(coordinator)
    # CPU backends need the gloo collectives implementation for real
    # cross-process collectives (the default CPU client rejects
    # "multiprocess computations"): the multihost failure drills and
    # the lockstep leg of scripts/bench_syncmode.py run 2-4 CPU ranks
    # through here.  Must be set BEFORE the backend initializes; inert
    # on accelerator backends, best-effort across jax versions.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:           # noqa: BLE001 — flag name drifts
        pass
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)


def build_mesh(*, dp: Optional[int] = None, tp: int = 1, sp: int = 1,
               pp: int = 1, ep: int = 1, devices=None) -> Mesh:
    """Mesh over all devices with named axes (pp, ep, sp, tp, dp); dp is
    inferred as the remainder when unset."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    fixed = tp * sp * pp * ep
    if n % fixed != 0:
        raise ValueError(
            f"{n} devices not divisible by tp*sp*pp*ep={fixed}")
    if dp is None:
        dp = n // fixed
    if dp * fixed != n:
        raise ValueError(f"dp*tp*sp*pp*ep={dp * fixed} != {n} devices")
    arr = np.asarray(devices).reshape(pp, ep, sp, tp, dp)
    return Mesh(arr, AXES)


def data_sharding(mesh: Mesh, batch_axis: int = 0) -> NamedSharding:
    """Shard the batch dimension across dp AND sp together — for pure
    data parallelism on a mesh that also carries an sp axis, both axes
    consume the global batch so no devices idle."""
    spec = [None] * (batch_axis + 1)
    spec[batch_axis] = ("dp", "sp") if mesh.shape.get("sp", 1) > 1 \
        else "dp"
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def dp_data_rank(mesh: Mesh) -> tuple:
    """(data_rank, data_num_ranks) for THIS process: which shard of
    the record stream it must feed.

    Derived from the mesh coordinates of the local devices, NOT the
    process rank — on a tp/sp-only mesh every process sits at dp
    index 0 and must feed IDENTICAL records (its model shard consumes
    the same replicated batch), while the process-rank sharding the
    cluster flags imply would feed each rank different data and
    silently train on inconsistent replicas.  Single-process meshes
    feed the whole stream (device_prefetch shards locally)."""
    if jax.process_count() <= 1:
        return 0, 1
    dp_total = mesh.shape.get("dp", 1)
    if dp_total <= 1:
        return 0, 1
    axes = list(mesh.axis_names)
    dp_axis = axes.index("dp")
    local_ids = {d.id for d in jax.local_devices()}
    rows = sorted({idx[dp_axis]
                   for idx in np.ndindex(mesh.devices.shape)
                   if mesh.devices[idx].id in local_ids})
    k = len(rows)
    if (k and rows == list(range(rows[0], rows[0] + k))
            and dp_total % k == 0 and rows[0] % k == 0):
        return rows[0] // k, dp_total // k
    # non-contiguous local dp rows (exotic device order): feed the
    # whole stream rather than misalign the local shard
    return 0, 1


# ---------------------------------------------------------------------------
# named-axis layouts (param/input spec construction)
#
# THE one spec-construction path: ParallelSolver (training) and
# BlobForward (serving / batch extract / validation) both consume
# MeshLayout, so a net's tp/ep partitioning can never diverge between
# the step that trains the weights and the forward that serves them.
# ---------------------------------------------------------------------------

TP_MIN_FEATURES = 1024  # shard only matmuls big enough to matter


def tp_param_specs(net, *, min_features: int = TP_MIN_FEATURES
                   ) -> Dict[str, Dict[str, P]]:
    """PartitionSpec per param blob: column-shard large IP/Embed weights
    over 'tp', replicate the rest (Megatron-style split on num_output)."""
    specs: Dict[str, Dict[str, P]] = {}
    by_name = {lp.name: lp for lp in net.compute_layers}
    for lname, blobs in net.param_layout.items():
        lp = by_name[lname]
        specs[lname] = {}
        for bname, shape, _ in blobs:
            spec = P()
            if lp.type == "InnerProduct" and bname == "weight":
                ipp = lp.inner_product_param
                n_out = int(ipp.num_output)
                if n_out >= min_features and not ipp.transpose:
                    spec = P("tp", None)     # (num_output, K) column split
                elif n_out >= min_features:
                    spec = P(None, "tp")
            elif lp.type == "InnerProduct" and bname == "bias":
                if int(lp.inner_product_param.num_output) >= min_features:
                    spec = P("tp")
            elif lp.type == "Embed" and bname == "weight":
                if int(lp.embed_param.num_output) >= min_features:
                    spec = P(None, "tp")     # (vocab, dim) dim split
            elif lp.type in ("LSTM", "RNN") and bname.startswith("W_x"):
                rp = lp.recurrent_param
                if int(rp.num_output) * 4 >= min_features:
                    spec = P("tp", None)     # (4N, D) gate split
            elif lp.type == "MixtureOfExperts" and bname in ("W1",
                                                             "W2"):
                spec = P("ep", None, None)   # expert-dim split
            specs[lname][bname] = spec
    return specs


def validate_param_specs(specs: Dict[str, Dict[str, P]],
                         shapes: Dict[str, Dict[str, tuple]],
                         mesh: Mesh) -> None:
    """Divisibility guard: every sharded param dim must divide by its
    mesh axis (an opaque XLA partition error otherwise)."""
    for ln, blobs in specs.items():
        for bn, spec in blobs.items():
            for dim_i, ax in enumerate(spec):
                if ax is None:
                    continue
                size = mesh.shape.get(ax, 1)
                dim = shapes[ln][bn][dim_i]
                if size > 1 and dim % size != 0:
                    raise ValueError(
                        f"layer {ln!r} blob {bn!r}: dim {dim_i} "
                        f"(size {dim}) not divisible by mesh axis "
                        f"{ax!r} (size {size}) — adjust "
                        f"num_experts/num_output or the mesh")


class MeshLayout:
    """Named-axis parameter + input layouts for one Net under one Mesh.

    Holds the PartitionSpecs/NamedShardings a forward or train step
    needs: tp/ep-sharded param layouts (with the divisibility guard),
    dp(+sp)-sharded input layouts, the replicated sharding, and a
    stable topology signature (the AOT cache namespace key).  Built
    once and shared — ParallelSolver derives its training shardings
    from it, and serving's BlobForward jits against the SAME object,
    which is what lets a net bigger than one device's HBM serve across
    the mesh with the exact layout training produced."""

    def __init__(self, net, mesh: Mesh, *, tensor_parallel: bool = True,
                 min_features: int = TP_MIN_FEATURES):
        self.net = net
        self.mesh = mesh
        self.tp_on = tensor_parallel and (
            mesh.shape.get("tp", 1) > 1 or mesh.shape.get("ep", 1) > 1)
        self.param_specs = (
            tp_param_specs(net, min_features=min_features) if self.tp_on
            else {ln: {bn: P() for bn, _, _ in blobs}
                  for ln, blobs in net.param_layout.items()})
        self.shapes = {ln: {bn: s for bn, s, _ in blobs}
                       for ln, blobs in net.param_layout.items()}
        validate_param_specs(self.param_specs, self.shapes, mesh)
        # -- pipeline stages (pp axis) ---------------------------------
        # pp > 1 cuts the net into contiguous stages (the roofline-
        # balanced partitioner shared with PipelineSolver) and pins
        # each stage's params to the submesh of its pp row: every
        # downstream consumer of param_sharding — place_params, the
        # zero-gather streaming loader, the serving registry — then
        # places or pages a stage's blobs straight onto that stage's
        # devices with no further routing logic.
        self.pp = 1
        self.stages: List[List[str]] = [
            [lp.name for lp in net.compute_layers]]
        self.stage_of_layer: Dict[str, int] = {}
        self.stage_meshes: List[Mesh] = [mesh]
        if int(mesh.shape.get("pp", 1)) > 1:
            from .pp import partition_layers   # lazy: avoids cycle
            self.stages = partition_layers(
                net, int(mesh.shape.get("pp", 1)))
            self.pp = len(self.stages)
            self.stage_meshes = [Mesh(mesh.devices[k], AXES[1:])
                                 for k in range(self.pp)]
        for k, names in enumerate(self.stages):
            for nme in names:
                self.stage_of_layer[nme] = k

        def _owner(ln: str) -> Mesh:
            return self.stage_meshes[self.stage_of_layer.get(ln, 0)] \
                if self.pp > 1 else mesh

        self.param_sharding = {
            ln: {bn: NamedSharding(_owner(ln), spec)
                 for bn, spec in blobs.items()}
            for ln, blobs in self.param_specs.items()}
        self.repl = replicated(mesh)
        self.stage_repl = ([replicated(m) for m in self.stage_meshes]
                           if self.pp > 1 else [self.repl])

    # -- inputs ---------------------------------------------------------
    def input_specs(self, net=None) -> Dict[str, P]:
        """Per-input PartitionSpec: batch sharded over dp; time-major
        (T, B, ·) tops shard batch on axis 1 and — when the mesh has an
        sp axis — their TIME axis over sp (sequence parallelism).  The
        optional `net` override serves forwards whose input geometry
        differs from the layout net (TEST-phase vs TRAIN-phase)."""
        net = net or self.net
        has_sp = dict(self.mesh.shape).get("sp", 1) > 1
        out = {}
        for name, shape, kind in net.input_specs:
            if kind.endswith(":T"):
                out[name] = P("sp", "dp") if has_sp else P(None, "dp")
            else:
                out[name] = P("dp")
        return out

    def input_shardings(self, net=None) -> Dict[str, NamedSharding]:
        # staged layouts feed inputs to stage 0's devices only — the
        # remaining stages receive activations, never inputs
        m = self.stage_meshes[0] if self.pp > 1 else self.mesh
        return {name: NamedSharding(m, spec)
                for name, spec in self.input_specs(net).items()}

    # -- placement ------------------------------------------------------
    def place_params(self, params) -> Dict:
        """device_put every param blob onto its layout sharding."""
        return {ln: {bn: jax.device_put(arr, self.param_sharding[ln][bn])
                     for bn, arr in blobs.items()}
                for ln, blobs in params.items()}

    def install_flash(self, fn):
        """A bare pallas_call cannot be GSPMD-partitioned, but attention
        is embarrassingly parallel over batch x heads — on meshes the
        dispatch is routed through shard_map (ops.layers.flash_mesh)
        and each device runs the kernel on its local block.  Single-
        device meshes call the kernel directly."""
        if self.mesh.devices.size <= 1:
            return fn

        def wrapped(*args, _f=fn):
            from ..ops.layers import flash_mesh
            with flash_mesh(self.mesh):  # active during TRACING
                return _f(*args)
        return wrapped

    # -- identity -------------------------------------------------------
    @property
    def dp(self) -> int:
        return self.mesh.shape.get("dp", 1)

    def describe(self) -> Dict[str, object]:
        """JSON-serializable layout summary (PipelineMetrics set_info,
        /healthz) — axes with extent > 1 plus the sharded blobs."""
        axes = {ax: int(n) for ax, n in self.mesh.shape.items() if n > 1}
        sharded = sorted(
            f"{ln}/{bn}:{','.join(str(a) for a in spec)}"
            for ln, blobs in self.param_specs.items()
            for bn, spec in blobs.items()
            if any(ax is not None for ax in spec))
        out = {"axes": axes or {"dp": 1},
               "devices": int(self.mesh.devices.size),
               "sharded_params": sharded}
        if self.pp > 1:
            out["pp_stages"] = [len(s) for s in self.stages]
        return out

    def signature(self) -> str:
        """Stable topology+layout signature: distinct meshes (or
        distinct param layouts under one mesh) must never share a
        compiled-program cache namespace (serving/aot.py).  Staged
        layouts append the pp stage boundaries — a staged and an
        unstaged program of the same net (or two cuts of it) compile
        to different executables and must never collide."""
        axes = ",".join(f"{ax}{self.mesh.shape.get(ax, 1)}"
                        for ax in self.mesh.axis_names)
        specs = ";".join(
            f"{ln}/{bn}={spec}"
            for ln in sorted(self.param_specs)
            for bn, spec in sorted(self.param_specs[ln].items())
            if any(ax is not None for ax in spec))
        sig = f"mesh({axes})|{specs}"
        if self.pp > 1:
            cuts = ",".join(str(len(s)) for s in self.stages)
            sig += f"|pp[{cuts}]"
        return sig


def lockstep_steps(total_records: int, batch_per_step: int,
                   num_ranks: int) -> int:
    """The minPartSize equalization invariant
    (`CaffeOnSpark.scala:185-200`): every rank must execute the SAME
    number of steps or a collective deadlocks the slice.  Returns the
    per-epoch step count = floor(min records per rank / batch)."""
    per_rank = total_records // num_ranks
    return max(0, per_rank // batch_per_step)
