"""Sequence/context parallelism: ring attention over the `sp` mesh axis.

The reference handles sequences only by single-device time-unrolled LSTM
(SURVEY §5.7 — no SP/CP of any kind).  Long-context support is
first-class here: sequences are sharded along time across the `sp` axis,
and attention runs as a **ring**: each step every device computes a
partial (flash-style, numerically stable online-softmax) attention
against its resident K/V block, then rotates K/V to its ring neighbor
with `lax.ppermute` — ICI traffic overlapping MXU compute, total memory
O(T/S) per device (Ring Attention, Liu et al. 2023; blockwise parallel
transformers).

`ring_attention` is the shard_map-ready collective op; `attention` is
the single-device reference implementation (also the parity oracle in
tests).  The LSTM path gets sequence scaling separately via its hoisted
(T·B, D)×(D, 4N) input projection, which XLA shards on `sp` when the
time axis carries a sharding.
"""

from __future__ import annotations

import math
from functools import partial
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

Array = jax.Array


def shard_map_nocheck(fn, mesh, in_specs, out_specs):
    """shard_map with the replication checker disabled: a pallas_call's
    outputs carry no varying-mesh-axes metadata, which the default
    checker rejects.  Tolerates the check_rep -> check_vma rename
    across jax versions — the ONE place that knows the kwarg (used by
    ring attention's flash mode and ops.layers' multi-device flash)."""
    import inspect
    sig = inspect.signature(shard_map).parameters
    kw = {k: False for k in ("check_rep", "check_vma") if k in sig}
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, **kw)


def attention(q: Array, k: Array, v: Array, *, causal: bool = False,
              q_offset: int = 0, k_offset: int = 0) -> Array:
    """Reference softmax attention. q,k,v: (B, H, T, D)."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[2])[:, None]
        kpos = k_offset + jnp.arange(k.shape[2])[None, :]
        s = jnp.where(qpos >= kpos, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def flash_block_size(t: int):
    """Flash kernel block for a local sequence extent t: 128 when it
    tiles; whole-shard for small shards; None = shape unsuited (a
    whole-shard block would blow VMEM) — callers fall back to the
    einsum accumulate.  The ONE place that knows the eligibility rule
    (used by the ring body here and ops.layers' mesh dispatch)."""
    if t % 128 == 0:
        return 128
    if t <= 256 and t % 8 == 0:
        return t
    return None


def _ring_attention_local(q: Array, k: Array, v: Array, *, axis_name: str,
                          causal: bool, flash=False) -> Array:
    """Per-shard body (inside shard_map): q,k,v are the LOCAL time blocks
    (B, H, T_local, D)."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, h, t_q, d = q.shape
    t_k = k.shape[2]
    scale = 1.0 / math.sqrt(d)
    qpos = idx * t_q + jnp.arange(t_q)           # global query positions
    bq, bk = flash_block_size(t_q), flash_block_size(t_k)
    use_flash = bool(flash) and bq is not None and bk is not None
    if use_flash:
        interp = flash == "interpret"
        if t_q == t_k:
            # fused + differentiable custom-VJP ring
            return _make_ring_flash(axis_name, causal, bq, bk,
                                    interp)(q, k, v)
        # unequal shard extents (cross-attention): fused Pallas
        # forward + einsum-ring backward (see _make_ring_flash_cross)
        return _make_ring_flash_cross(axis_name, causal, bq, bk,
                                      interp)(q, k, v)

    def accumulate(m, l, o, k_blk, v_blk, src):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk) * scale
        if causal:
            kpos = src * t_k + jnp.arange(t_k)
            mask = qpos[:, None] >= kpos[None, :]
            s = jnp.where(mask[None, None], s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # guard fully-masked rows: exp against a finite max
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe[..., None])
        corr = jnp.exp(m - m_safe)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p,
                                                 v_blk)
        return m_new, l_new, o_new

    def body(step, carry):
        m, l, o, k_blk, v_blk = carry
        # rotate K/V around the ring (neighbor exchange on ICI), then
        # accumulate — block 0 is handled before the loop, so no
        # superfluous rotation happens after the last accumulation
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        m, l, o = accumulate(m, l, o, k_blk, v_blk, (idx - step) % n)
        return m, l, o, k_blk, v_blk

    # derive from q so the carry is device-varying like the loop outputs
    # (shard_map VMA typing requires carry in/out types to match)
    m0 = jnp.full(q.shape[:-1], -jnp.inf, q.dtype) + q[..., 0] * 0
    l0 = jnp.zeros(q.shape[:-1], q.dtype) + q[..., 0] * 0
    o0 = jnp.zeros(q.shape, q.dtype) + q * 0
    m, l, o = accumulate(m0, l0, o0, k, v, idx)
    m, l, o, _, _ = lax.fori_loop(1, n, body, (m, l, o, k, v))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def _flash_ring_forward(q: Array, k: Array, v: Array, *, axis_name: str,
                        causal: bool, bq: int, bk: int, interpret: bool):
    """Fused flash ring forward (the ONE copy of the ring loop): K/V
    shards rotate on ICI ppermute, each hop folds into the
    online-softmax (m, l, acc) carry via flash_block_update.  Returns
    (out, lse); lse = m + log(l) is the VJP residual for the
    differentiable wrapper (unused by the forward-only caller).
    Causal runs skip fully-masked hops (K entirely after Q) — on
    average (n-1)/2 kernel launches saved per device per pass."""
    from ..ops.pallas_kernels import flash_block_update
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, h, t_q, d = q.shape
    t_k = k.shape[2]
    bh = b * h

    def hop(m, l, o, k_blk, v_blk, src):
        mf, lf, of = flash_block_update(
            q.reshape(bh, t_q, d), k_blk.reshape(bh, t_k, d),
            v_blk.reshape(bh, t_k, d), m.reshape(bh, t_q),
            l.reshape(bh, t_q), o.reshape(bh, t_q, d),
            idx * t_q, src * t_k, causal=causal, block_q=bq,
            block_k=bk, interpret=interpret)
        return (mf.reshape(b, h, t_q), lf.reshape(b, h, t_q),
                of.reshape(b, h, t_q, d))

    def maybe_hop(m, l, o, k_blk, v_blk, src):
        if not causal:
            return hop(m, l, o, k_blk, v_blk, src)
        # contributes iff the last q row can see the first k row
        return lax.cond((idx + 1) * t_q > src * t_k,
                        lambda m_, l_, o_: hop(m_, l_, o_, k_blk,
                                               v_blk, src),
                        lambda m_, l_, o_: (m_, l_, o_),
                        m, l, o)

    def body(step, carry):
        m, l, o, k_blk, v_blk = carry
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        m, l, o = maybe_hop(m, l, o, k_blk, v_blk, (idx - step) % n)
        return m, l, o, k_blk, v_blk

    # device-varying carry init (shard_map VMA typing), f32 stats
    m0 = jnp.full(q.shape[:-1], -jnp.inf, jnp.float32) \
        + q[..., 0].astype(jnp.float32) * 0
    l0 = jnp.zeros(q.shape[:-1], jnp.float32) \
        + q[..., 0].astype(jnp.float32) * 0
    o0 = jnp.zeros(q.shape, jnp.float32) + q.astype(jnp.float32) * 0
    m, l, o = maybe_hop(m0, l0, o0, k, v, idx)
    m, l, o, _, _ = lax.fori_loop(1, n, body, (m, l, o, k, v))
    l_safe = jnp.maximum(l, 1e-30)
    out = (o / l_safe[..., None]).astype(q.dtype)
    lse = m + jnp.log(l_safe)                        # (B, H, t_q) f32
    return out, lse


def _make_ring_flash(axis_name: str, causal: bool, bq: int, bk: int,
                     interpret: bool):
    """Differentiable fused ring attention (equal shard extents).

    Forward: _flash_ring_forward, keeping the log-sum-exp residual.

    Backward: a second ring pass.  Each device keeps its K/V shard
    resident and the (q, dO, lse, delta, dq-accumulator) tuple rotates;
    at each hop the resident shard contributes via the flash backward
    kernels (flash_bwd_block) — causal kernels for the diagonal pair,
    unmasked for fully-visible pairs (visitor origin j > idx), skipped
    when fully masked (j < idx).  dk/dv accumulate at home in f32; dq
    co-rotates with its q-group and one final ppermute returns it.
    This is the standard ring-attention backward (the memory-efficient
    counterpart of differentiating the einsum accumulate, which would
    rematerialize (T_local, T_local) score blocks per hop)."""
    from ..ops.pallas_kernels import flash_bwd_block

    def _fwd_pass(q, k, v):
        return _flash_ring_forward(q, k, v, axis_name=axis_name,
                                   causal=causal, bq=bq, bk=bk,
                                   interpret=interpret)

    @jax.custom_vjp
    def rf(q, k, v):
        return _fwd_pass(q, k, v)[0]

    def fwd(q, k, v):
        out, lse = _fwd_pass(q, k, v)
        return out, (q, k, v, out, lse)

    def bwd(res, do):
        q, k, v, out, lse = res
        n = lax.psum(1, axis_name)
        idx = lax.axis_index(axis_name)
        b, h, t, d = q.shape
        bh = b * h
        qf = q.reshape(bh, t, d)
        kf = k.reshape(bh, t, d)
        vf = v.reshape(bh, t, d)
        dof = do.reshape(bh, t, d).astype(qf.dtype)
        lsef = lse.reshape(bh, t)
        delta = jnp.sum(dof.astype(jnp.float32)
                        * out.reshape(bh, t, d).astype(jnp.float32),
                        axis=-1)                      # (bh, t) f32

        def block(vq, vdo, vlse, vdelta, diag):
            # f32 outputs straight from the kernels: per-hop partials
            # must not round to bf16 before the ring accumulation
            return flash_bwd_block(
                vq, kf, vf, vdo, vlse, vdelta, causal=diag,
                block_q=bq, block_k=bk, interpret=interpret,
                out_dtype=jnp.float32)

        # s = 0: the diagonal pair (visitor == home shard)
        dq0, dk0, dv0 = block(qf, dof, lsef, delta, diag=causal)

        def body(s, carry):
            vq, vdo, vlse, vdelta, dqv, dk, dv = carry
            prm = [(i, (i + 1) % n) for i in range(n)]
            vq, vdo, vlse, vdelta, dqv = (
                lax.ppermute(x, axis_name, prm)
                for x in (vq, vdo, vlse, vdelta, dqv))
            j = (idx - s) % n          # visiting q-group's home shard

            def contribute(_):
                return block(vq, vdo, vlse, vdelta, diag=False)

            def skip(_):
                return (jnp.zeros((bh, t, d), jnp.float32),
                        jnp.zeros((bh, t, d), jnp.float32),
                        jnp.zeros((bh, t, d), jnp.float32))

            if causal:
                # visitor attends this shard's K/V iff it sits later in
                # the global sequence (diagonal already done at s=0)
                dqh, dkh, dvh = lax.cond(j > idx, contribute, skip,
                                         None)
            else:
                dqh, dkh, dvh = contribute(None)
            return (vq, vdo, vlse, vdelta, dqv + dqh, dk + dkh,
                    dv + dvh)

        carry = (qf, dof, lsef, delta, dq0, dk0, dv0)
        _, _, _, _, dqv, dk32, dv32 = lax.fori_loop(1, n, body, carry)
        # dq co-rotated n-1 times with its q-group: one more hop home
        prm = [(i, (i + 1) % n) for i in range(n)]
        dqv = lax.ppermute(dqv, axis_name, prm)
        return (dqv.reshape(b, h, t, d).astype(q.dtype),
                dk32.reshape(b, h, t, d).astype(k.dtype),
                dv32.reshape(b, h, t, d).astype(v.dtype))

    rf.defvjp(fwd, bwd)
    return rf


def _make_ring_flash_cross(axis_name: str, causal: bool, bq: int,
                           bk: int, interpret: bool):
    """Differentiable fused ring attention for UNEQUAL shard extents
    (cross-attention: T_q ≠ T_k per shard).

    Forward: the same fused Pallas ring as the equal-extent path
    (_flash_ring_forward handles t_q ≠ t_k), keeping the lse residual.

    Backward: an einsum ring pass, NOT the flash backward kernels —
    those assume square (T, T) block geometry (flash_bwd_block derives
    the K/V specs from q's extent).  Each hop rematerializes one
    (t_q_local, t_k_local) score block from the saved lse, which is
    exactly the memory the fused path saves on the forward; for
    cross-attention the K/V extent is typically the short encoder side,
    so the block stays small.  Ring choreography matches
    _make_ring_flash's backward: K/V stay home, (q, dO, lse, delta, dq)
    rotate, dk/dv accumulate at home in f32, one final ppermute sends
    dq home.  Causal masking uses GLOBAL positions (visitor q-group j's
    offset j·t_q vs home K offset idx·t_k) — the equal-extent path can
    reason per-pair, unequal extents cannot."""

    def _fwd_pass(q, k, v):
        return _flash_ring_forward(q, k, v, axis_name=axis_name,
                                   causal=causal, bq=bq, bk=bk,
                                   interpret=interpret)

    @jax.custom_vjp
    def rf(q, k, v):
        return _fwd_pass(q, k, v)[0]

    def fwd(q, k, v):
        out, lse = _fwd_pass(q, k, v)
        return out, (q, k, v, out, lse)

    def bwd(res, do):
        q, k, v, out, lse = res
        n = lax.psum(1, axis_name)
        idx = lax.axis_index(axis_name)
        b, h, t_q, d = q.shape
        t_k = k.shape[2]
        scale = 1.0 / math.sqrt(d)
        kf = k.astype(jnp.float32)
        vf = v.astype(jnp.float32)
        do32 = do.astype(jnp.float32)
        delta = jnp.sum(do32 * out.astype(jnp.float32),
                        axis=-1)                     # (B, H, t_q) f32
        kpos = idx * t_k + jnp.arange(t_k)           # home K positions

        # HIGHEST precision: on TPU a DEFAULT-precision f32 einsum is a
        # single bf16 MXU pass — measured max score error 1.2e-2 at the
        # test shape, which exp() turns into an 8e-4 p-inconsistency
        # against the kernel's lse and a >1e-2 dq violation on sharp
        # causal rows.  HIGHEST (multi-pass f32) recovers the kernel's
        # accuracy (p error 2e-4 measured on chip).  The
        # lossless-re-round argument (bf16 activations upcast to f32
        # round-trip exactly through a DEFAULT bf16 pass) applies ONLY
        # to einsums whose f32 operands are such upcasts — the score
        # and dp products below.  `p` (exp of shifted scores) and `ds`
        # are GENUINELY f32-valued intermediates with no bf16
        # preimage, so every einsum consuming them runs HIGHEST
        # unconditionally; rounding them through a bf16 MXU pass would
        # leave the bf16-input backward less accurate than the forward
        # kernel it must match (ADVICE r05).
        hi = (jax.lax.Precision.HIGHEST
              if any(a.dtype == jnp.float32 for a in (q, k, v))
              else jax.lax.Precision.DEFAULT)
        hi_pd = jax.lax.Precision.HIGHEST   # p/ds-consuming einsums

        def pair(vq, vdo, vlse, vdelta, j):
            """Visitor q-group (home shard j) against the resident K/V:
            p from the saved lse, then ds → (dq, dk, dv) partials."""
            s = jnp.einsum("bhqd,bhkd->bhqk", vq.astype(jnp.float32),
                           kf, precision=hi) * scale
            p = jnp.exp(s - vlse[..., None])
            if causal:
                qpos = j * t_q + jnp.arange(t_q)
                p = jnp.where((qpos[:, None] >= kpos[None, :])
                              [None, None], p, 0.0)
            dp = jnp.einsum("bhqd,bhkd->bhqk", vdo, vf, precision=hi)
            ds = p * (dp - vdelta[..., None])
            dqh = jnp.einsum("bhqk,bhkd->bhqd", ds, kf,
                             precision=hi_pd) * scale
            dkh = jnp.einsum("bhqk,bhqd->bhkd", ds,
                             vq.astype(jnp.float32),
                             precision=hi_pd) * scale
            dvh = jnp.einsum("bhqk,bhqd->bhkd", p, vdo,
                             precision=hi_pd)
            return dqh, dkh, dvh

        def maybe_pair(vq, vdo, vlse, vdelta, j):
            if not causal:
                return pair(vq, vdo, vlse, vdelta, j)
            # visitor contributes iff its last q row can see the home
            # shard's first k row (mirror of the forward's hop skip)
            return lax.cond(
                (j + 1) * t_q > idx * t_k,
                lambda _: pair(vq, vdo, vlse, vdelta, j),
                lambda _: (jnp.zeros((b, h, t_q, d), jnp.float32),
                           jnp.zeros((b, h, t_k, d), jnp.float32),
                           jnp.zeros((b, h, t_k, d), jnp.float32)),
                None)

        dq0, dk0, dv0 = maybe_pair(q, do32, lse, delta, idx)

        def body(s, carry):
            vq, vdo, vlse, vdelta, dqv, dk, dv = carry
            prm = [(i, (i + 1) % n) for i in range(n)]
            vq, vdo, vlse, vdelta, dqv = (
                lax.ppermute(x, axis_name, prm)
                for x in (vq, vdo, vlse, vdelta, dqv))
            j = (idx - s) % n         # visiting q-group's home shard
            dqh, dkh, dvh = maybe_pair(vq, vdo, vlse, vdelta, j)
            return (vq, vdo, vlse, vdelta, dqv + dqh, dk + dkh,
                    dv + dvh)

        carry = (q, do32, lse, delta, dq0, dk0, dv0)
        _, _, _, _, dqv, dk32, dv32 = lax.fori_loop(1, n, body, carry)
        prm = [(i, (i + 1) % n) for i in range(n)]
        dqv = lax.ppermute(dqv, axis_name, prm)
        return (dqv.astype(q.dtype), dk32.astype(k.dtype),
                dv32.astype(v.dtype))

    rf.defvjp(fwd, bwd)
    return rf


def ring_attention(q: Array, k: Array, v: Array, mesh: Mesh, *,
                   causal: bool = False, axis_name: str = "sp",
                   flash=False) -> Array:
    """Sequence-parallel attention: (B, H, T, D) with T sharded on
    `axis_name`.  Returns output with the same sharding.

    flash: False (default, einsum accumulate) | True (fused Pallas
    ring, now DIFFERENTIABLE for equal shard extents — custom-VJP
    second ring pass with the flash backward kernels, see
    _make_ring_flash) | "interpret" (same, on CPU for tests)."""
    spec = P(None, None, axis_name, None)
    local = partial(_ring_attention_local, axis_name=axis_name,
                    causal=causal, flash=flash)
    if flash:
        fn = shard_map_nocheck(local, mesh, (spec, spec, spec), spec)
    else:
        fn = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec)
    return fn(q, k, v)


def sp_shard_time(x: Array, mesh: Mesh, *, time_axis: int = 2,
                  axis_name: str = "sp") -> Array:
    """Place an activation with its time axis sharded over sp."""
    spec = [None] * (time_axis + 1)
    spec[time_axis] = axis_name
    return jax.device_put(x, NamedSharding(mesh, P(*spec)))
