"""Sequence/context parallelism: ring attention over the `sp` mesh axis.

The reference handles sequences only by single-device time-unrolled LSTM
(SURVEY §5.7 — no SP/CP of any kind).  Long-context support is
first-class here: sequences are sharded along time across the `sp` axis,
and attention runs as a **ring**: each step every device computes a
partial (flash-style, numerically stable online-softmax) attention
against its resident K/V block, then rotates K/V to its ring neighbor
with `lax.ppermute` — ICI traffic overlapping MXU compute, total memory
O(T/S) per device (Ring Attention, Liu et al. 2023; blockwise parallel
transformers).

`ring_attention` is the shard_map-ready collective op; `attention` is
the single-device reference implementation (also the parity oracle in
tests).  The LSTM path gets sequence scaling separately via its hoisted
(T·B, D)×(D, 4N) input projection, which XLA shards on `sp` when the
time axis carries a sharding.
"""

from __future__ import annotations

import math
from functools import partial
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

Array = jax.Array


def attention(q: Array, k: Array, v: Array, *, causal: bool = False,
              q_offset: int = 0, k_offset: int = 0) -> Array:
    """Reference softmax attention. q,k,v: (B, H, T, D)."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[2])[:, None]
        kpos = k_offset + jnp.arange(k.shape[2])[None, :]
        s = jnp.where(qpos >= kpos, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _ring_attention_local(q: Array, k: Array, v: Array, *, axis_name: str,
                          causal: bool) -> Array:
    """Per-shard body (inside shard_map): q,k,v are the LOCAL time blocks
    (B, H, T_local, D)."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, h, t_q, d = q.shape
    t_k = k.shape[2]
    scale = 1.0 / math.sqrt(d)
    qpos = idx * t_q + jnp.arange(t_q)           # global query positions

    def accumulate(m, l, o, k_blk, v_blk, src):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk) * scale
        if causal:
            kpos = src * t_k + jnp.arange(t_k)
            mask = qpos[:, None] >= kpos[None, :]
            s = jnp.where(mask[None, None], s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # guard fully-masked rows: exp against a finite max
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe[..., None])
        corr = jnp.exp(m - m_safe)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p,
                                                 v_blk)
        return m_new, l_new, o_new

    def body(step, carry):
        m, l, o, k_blk, v_blk = carry
        # rotate K/V around the ring (neighbor exchange on ICI), then
        # accumulate — block 0 is handled before the loop, so no
        # superfluous rotation happens after the last accumulation
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        m, l, o = accumulate(m, l, o, k_blk, v_blk, (idx - step) % n)
        return m, l, o, k_blk, v_blk

    # derive from q so the carry is device-varying like the loop outputs
    # (shard_map VMA typing requires carry in/out types to match)
    m0 = jnp.full_like(q[..., 0], -jnp.inf)
    l0 = jnp.zeros_like(q[..., 0])
    o0 = jnp.zeros_like(q)
    m, l, o = accumulate(m0, l0, o0, k, v, idx)
    m, l, o, _, _ = lax.fori_loop(1, n, body, (m, l, o, k, v))
    return o / jnp.maximum(l, 1e-30)[..., None]


def ring_attention(q: Array, k: Array, v: Array, mesh: Mesh, *,
                   causal: bool = False, axis_name: str = "sp") -> Array:
    """Sequence-parallel attention: (B, H, T, D) with T sharded on
    `axis_name`.  Returns output with the same sharding."""
    spec = P(None, None, axis_name, None)
    fn = shard_map(
        partial(_ring_attention_local, axis_name=axis_name, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def sp_shard_time(x: Array, mesh: Mesh, *, time_axis: int = 2,
                  axis_name: str = "sp") -> Array:
    """Place an activation with its time axis sharded over sp."""
    spec = [None] * (time_axis + 1)
    spec[time_axis] = axis_name
    return jax.device_put(x, NamedSharding(mesh, P(*spec)))
