"""Parallelism strategies over the device mesh.

Parity surface (SURVEY §2.7): data parallelism — intra-node P2PSync +
inter-node sharded socket/RDMA exchange in the reference — becomes GSPMD
over a named mesh (`dp.ParallelSolver`).  Extensions beyond the
reference: tensor parallelism (`dp.tp_param_specs`), sequence/context
parallelism via ring attention (`sp.ring_attention`), and the explicit
communication-efficient gradient exchange (`gradsync.GradSync`:
bucketed backward-overlap, quantized wire, hierarchical reduction —
COS_GRAD_SYNC).
"""

from .dp import ParallelSolver, tp_param_specs
from .gradsync import GradSync, GradSyncPlan, build_plan, make_gradsync
from .mesh import (MeshLayout, build_mesh, data_sharding,
                   distributed_init, dp_data_rank, lockstep_steps,
                   parse_mesh_spec, replicated)
from .pp import PipelineSolver, partition_layers
from .sp import attention, ring_attention, sp_shard_time
from .syncmode import (AsyncSync, LocalSGDSync, ParamStore, SyncPolicy,
                       env_sync_mode, make_sync, resolve_policy)
