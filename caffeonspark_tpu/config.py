"""Config: the full CLI flag surface of the reference driver.

Mirrors `caffe-grid/.../Config.scala` — option table :407-437, solver/net
prototxt parsing on the driver :70-71, train/test data-layer location by
`include.phase` :73-86, clusterSize derivation :459-474, connection enum
:227-236.  The connection flag is kept for CLI compatibility but maps to
the mesh backend (ICI/DCN collectives) — there is no RDMA/SOCKET code to
select anymore.
"""

from __future__ import annotations

import argparse
import os
from typing import List, Optional

from .proto import (NetParameter, Phase, SolverParameter, read_net,
                    read_solver)

CONNECTION_NONE = 0
CONNECTION_MESH = 1      # reference: RDMA (default)
CONNECTION_SOCKET = 2    # reference: ethernet sockets


def build_argparser() -> argparse.ArgumentParser:
    """Flag table parity with Config.scala:407-437."""
    p = argparse.ArgumentParser(prog="CaffeOnSparkTPU", add_help=True)
    a = p.add_argument
    a("-conf", dest="protoFile", default="",
      help="solver configuration (prototxt)")
    a("-train", dest="isTraining", action="store_true",
      help="training mode")
    a("-test", dest="isTest", action="store_true", help="test mode")
    a("-features", dest="features", default="",
      help="comma-separated blob names for feature extraction")
    a("-label", dest="label", default="",
      help="label blob name (feature extraction)")
    a("-outputFormat", dest="outputFormat", default="json",
      help="json | parquet")
    a("-model", dest="modelPath", default="",
      help="model file path (in/out)")
    a("-output", dest="outputPath", default="",
      help="output path for features/test results")
    a("-devices", dest="devices", type=int, default=0,
      help="devices per executor (0 = all local)")
    a("-persistent", dest="isPersistent", action="store_true",
      help="cache decoded source records in memory after epoch 0 "
           "(sourceRDD.persist analog)")
    a("-async_snapshot", dest="asyncSnapshot", action="store_true",
      help="write snapshots on a background thread (write-behind): the "
           "train loop stalls only for the device_get, not the file/"
           "remote I/O")
    a("-snapshot", dest="snapshotStateFile", default="",
      help="solverstate to resume from")
    a("-weights", dest="snapshotModelFile", default="",
      help="caffemodel to finetune from")
    a("-connection", dest="connection", default="",
      help="ethernet | infiniband (compat; both → mesh collectives)")
    a("-resize", dest="resize", action="store_true",
      help="resize images to layer dims")
    a("-clusterSize", dest="clusterSize", type=int, default=1,
      help="number of executor processes")
    a("-lmdb_partitions", dest="lmdb_partitions", type=int, default=0,
      help="LMDB RDD partitions (default clusterSize)")
    a("-imageRoot", dest="imageRoot", default="",
      help="image root dir (conversion tools)")
    a("-labelFile", dest="labelFile", default="",
      help="label file (conversion tools)")
    a("-captionFile", dest="captionFile", default="",
      help="COCO caption json (tools)")
    a("-captionLength", dest="captionLength", type=int, default=20,
      help="max caption length")
    a("-vocabSize", dest="vocabSize", type=int, default=10000,
      help="vocabulary size")
    a("-imageCaptionDFDir", dest="imageCaptionDFDir", default="",
      help="image-caption dataframe dir")
    a("-vocabDir", dest="vocabDir", default="",
      help="vocabulary dir")
    a("-embeddingDFDir", dest="embeddingDFDir", default="",
      help="embedding dataframe dir")
    # online serving mode (serving subsystem, not in the reference)
    a("-serve", dest="serve", action="store_true",
      help="online inference serving: dynamic micro-batching over a "
           "JSON HTTP front end (weights from -model/-weights; knobs "
           "COS_SERVE_MAX_BATCH / COS_SERVE_MAX_WAIT_MS / "
           "COS_SERVE_QUEUE_DEPTH)")
    a("-servePort", dest="servePort", type=int, default=0,
      help="serving HTTP port (0 = ephemeral, printed at startup)")
    a("-serveHost", dest="serveHost", default="127.0.0.1",
      help="serving bind address (loopback by default; the unauth'd "
           "/v1/reload endpoint makes wider binds an explicit opt-in)")
    a("-serveMesh", dest="serveMesh", default="",
      help="serving mesh spec dp[,tp[,sp[,ep]]] or key=value with "
           "pp=N (same grammar as -mesh): mesh-parallel forward with "
           "params tp/ep-sharded and the batch dp-sharded, serving "
           "nets bigger than one device; pp=N cuts the forward into "
           "N roofline-balanced stages, each an independent HBM "
           "paging unit; env equivalents COS_SERVE_MESH (same spec) "
           "and COS_SERVE_TP=N (tp-only shorthand)")
    a("-serveReplicas", dest="serveReplicas", type=int, default=0,
      help="fleet mode: N replica serving processes behind a "
           "least-outstanding router with retry + rolling hot-swap "
           "(0/unset → COS_SERVE_REPLICAS, default 1 = single "
           "process; COS_AOT_CACHE_DIR shares compiled programs so "
           "replicas warm-start)")
    # continuous deployment (deploy/ subsystem, not in the reference)
    a("-deploy", dest="deploy", action="store_true",
      help="canary-gated continuous deployment: follow a growing "
           "stream directory (the TRAIN data layer, source_class "
           "StreamingDir), fine-tune from the newest snapshot each "
           "round, canary-gate the candidate against the incumbent "
           "on the held-out TEST data layer, and publish accepted "
           "rounds to the serving fleet via rolling reload with "
           "auto-rollback (knobs COS_DEPLOY_*)")
    a("-deployRounds", dest="deployRounds", type=int, default=0,
      help="rounds the -deploy loop runs (0/unset → "
           "COS_DEPLOY_ROUNDS, default 3)")
    # mesh extensions (not in the reference)
    a("-mesh", dest="mesh", default="",
      help="mesh spec dp[,tp[,sp[,ep]]] per process")
    a("-server", dest="server", default="",
      help="multi-host coordinator host:port")
    a("-rank", dest="rank", type=int, default=0, help="process rank")
    return p


def resolve_net_path(solver_path: str, net_path: str) -> str:
    """Resolve the solver's `net:` reference: absolute/cwd-relative, else
    look next to the solver file (reference configs use repo-relative
    paths like "CaffeOnSpark/data/...")."""
    if not os.path.isabs(net_path) and not os.path.exists(net_path):
        cand = os.path.join(os.path.dirname(os.path.abspath(solver_path)),
                            os.path.basename(net_path))
        if os.path.exists(cand):
            return cand
    return net_path


class Config:
    """Parsed CLI + solver/net prototxt (driver side)."""

    def __init__(self, args: Optional[List[str]] = None, **overrides):
        ns, _ = build_argparser().parse_known_args(args or [])
        for k, v in overrides.items():
            setattr(ns, k, v)
        self.args = ns
        for k in vars(ns):
            setattr(self, k, getattr(ns, k))

        self.solverParameter: Optional[SolverParameter] = None
        self.netParam: Optional[NetParameter] = None
        if self.protoFile:
            self.solverParameter = read_solver(self.protoFile)
            self.netParam = read_net(
                resolve_net_path(self.protoFile, self.solverParameter.net))
        if self.lmdb_partitions == 0:
            self.lmdb_partitions = self.clusterSize

    # -- data-layer location by phase (Config.scala:73-86) ---------------
    def _data_layer_ids(self, phase: int) -> List[int]:
        out = []
        if self.netParam is None:
            return out
        from .net import layer_included
        from .proto import NetState
        state = NetState(phase=phase)
        for i, lyr in enumerate(self.netParam.layer):
            if lyr.type not in ("MemoryData", "CoSData", "Data",
                                "HDF5Data", "ImageData"):
                continue
            # full NetStateRule semantics: include rules OR'd, exclude
            # honored, rule-less layers in every phase
            if layer_included(lyr, state):
                out.append(i)
        return out

    @property
    def train_data_layer_id(self) -> int:
        ids = self._data_layer_ids(Phase.TRAIN)
        return ids[0] if ids else -1

    @property
    def test_data_layer_id(self) -> int:
        ids = self._data_layer_ids(Phase.TEST)
        return ids[0] if ids else -1

    def train_data_layer(self):
        i = self.train_data_layer_id
        return self.netParam.layer[i] if i >= 0 else None

    def test_data_layer(self):
        i = self.test_data_layer_id
        return self.netParam.layer[i] if i >= 0 else None

    # -- validation (Config.scala:459-474 sanity analog) -----------------
    def validate(self) -> None:
        if self.snapshotStateFile and not self.snapshotModelFile:
            raise ValueError(
                "-snapshot requires -weights (state without model)")
        if self.isTraining and self.train_data_layer_id < 0:
            raise ValueError("no TRAIN-phase data layer in net prototxt")
        if getattr(self, "serve", False):
            if self.netParam is None:
                raise ValueError("-serve needs -conf (solver prototxt "
                                 "resolving a net)")
            if not (self.modelPath or self.snapshotModelFile
                    or self.snapshotStateFile):
                raise ValueError("-serve needs trained weights: "
                                 "-model, -weights, or -snapshot")
        if getattr(self, "deploy", False):
            if self.netParam is None:
                raise ValueError("-deploy needs -conf (solver "
                                 "prototxt resolving a net)")
            if not self.outputPath:
                raise ValueError("-deploy needs -output (snapshot "
                                 "lineage directory)")
            if self.train_data_layer_id < 0:
                raise ValueError("-deploy needs a TRAIN-phase data "
                                 "layer (the stream to follow)")
            if not self.features:
                raise ValueError("-deploy needs -features naming the "
                                 "logits blob the canary gate scores")
