"""Per-stage ingest/step timeline metrics for the pipelined runtime.

The reference executor has no visibility into where a training step's
wall-time goes (queue wait vs transform vs H2D vs solver); this module
gives the TPU pipeline that visibility cheaply: lock-guarded ring
buffers per stage, O(1) per sample, summarized on demand.

The serving subsystem records its stages (latency / assemble / pack /
fwd / exec_wait / time_to_first_flush series, queue_depth /
batch_fill gauges, served/rejected/expired and per-bucket
flush_bucket_<n> counters) through the same classes, so serving
metrics dump in this exact JSON format.  The fleet layer adds its own
series in the same shape: `route` (router-observed request time,
retries included), `replica_startup` / `replica_rejoin` (spawn →
healthy wall time, cold vs restart-on-death), counters `routed` /
`retries` / `retry_429` / `retry_503` / `retry_conn` /
`replica_restarts` / `rolling_reloads` (one per fleet-wide swap
operation; `replica_reloads` counts per-replica swaps), and a
per-replica
state/outstanding/requests table under `replicas` in the router
summary.

Stage names used by the training runtime:
  queue_wait  solver thread blocked in next(gen) waiting for a batch
  pack        transformer-pool decode/augment/pack of one batch
  stack       np.stack of K packed batches into one (K, batch…) block
              (fused multi-step path, COS_STEPS_PER_LOOP > 1)
  stage       device_put / make_array + device-transform dispatch (H2D)
  step        jitted train-step call (on accelerators this is dispatch
              wall-time — the async runtime returns before compute
              finishes; per-step throughput comes from mark_step());
              for a fused chunk this is the recovered chunk_time/K
  scan_step   one fused K-step dispatch (whole-chunk wall time)
  comm        injected gradient-exchange floor sleeps (bench drills:
              COS_FAULT_COMM_NS_PER_BYTE models the exposed wire time
              of the COS_GRAD_SYNC plan)

Static run facts ride in the same JSON via `set_info`: the trainer
publishes the gradient-exchange plan as `info.comm` (per-step wire
bytes, bucket count and sizes, wire dtype, mode), the resolved
fault-injection plan as `info.faults` (tools/chaos.py — {"active":
false} on clean runs, the exact injectors otherwise, so every drill
and bench artifact is self-describing), and the sync-mode policy +
final exchange counts as `info.sync` (COS_SYNC_MODE, K/staleness,
exchanges / skipped / adopted / timeouts / max_gap).  The relaxed
sync modes also record a `sync_exchange` stage series (host-side
round-average / global-merge wall time).  The continuous-deployment
controller publishes `info.deploy` the same way (incumbent, verdict
history, per-state counts, knobs) plus a `deploy_round` wall series
and `deploy_<verdict>` counters.

Stages are NOT disjoint when staging (and, on the inline path, packing)
runs synchronously inside next(gen): there queue_wait SUBSUMES the pack
and stage samples recorded for the same batch, so per-stage totals can
legitimately exceed wall-time.  They are disjoint in the fully
pipelined configuration (pool + background stager), where queue_wait
measures pure starvation.

Counters (dropped batches, ragged-tail records) and gauges (queue
depths, sampled each step) ride along in the same summary.

The observability layer (caffeonspark_tpu/obs) builds on this format
without a second bookkeeping path: `obs/prom.py` renders the same
summary dict as Prometheus exposition (`/metrics?format=prom`), and
`MetricsFlusher` (COS_METRICS_FLUSH_S) background-flushes it to
`<output>/metrics.json` through the fsync'd atomic-write path so a
SIGKILLed run keeps telemetry no older than one interval.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional

_DEFAULT_CAPACITY = 8192


class _Series:
    """Total/count plus a bounded sample ring for percentiles."""

    __slots__ = ("total", "count", "max", "_ring", "_cap", "_i")

    def __init__(self, capacity: int):
        self.total = 0.0
        self.count = 0
        self.max = 0.0
        self._ring: List[float] = []
        self._cap = capacity
        self._i = 0

    def add(self, v: float):
        self.total += v
        self.count += 1
        if v > self.max:
            self.max = v
        if len(self._ring) < self._cap:
            self._ring.append(v)
        else:
            self._ring[self._i] = v
            self._i = (self._i + 1) % self._cap

    def summary(self) -> Dict[str, float]:
        s = sorted(self._ring)
        n = len(s)

        def pct(p):
            return s[min(n - 1, int(p * n))] if n else 0.0

        return {
            "count": self.count,
            "total_s": round(self.total, 6),
            "mean_ms": round(1e3 * self.total / self.count, 4)
            if self.count else 0.0,
            "p50_ms": round(1e3 * pct(0.50), 4),
            "p95_ms": round(1e3 * pct(0.95), 4),
            "p99_ms": round(1e3 * pct(0.99), 4),
            "p99_9_ms": round(1e3 * pct(0.999), 4),
            "max_ms": round(1e3 * self.max, 4),
        }


class _Gauge:
    """Sampled depth/level: count, mean, max."""

    __slots__ = ("total", "count", "max")

    def __init__(self):
        self.total = 0.0
        self.count = 0
        self.max = 0.0

    def observe(self, v: float):
        self.total += v
        self.count += 1
        if v > self.max:
            self.max = v

    def summary(self) -> Dict[str, float]:
        return {
            "samples": self.count,
            "mean": round(self.total / self.count, 3) if self.count else 0.0,
            "max": self.max,
        }


class PipelineMetrics:
    """Thread-safe per-stage timeline: durations, counters, gauges, and
    step timestamps for steady-state throughput."""

    def __init__(self, capacity: int = _DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._series: Dict[str, _Series] = {}
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, _Gauge] = {}
        self._steps: List[float] = []
        self._info: Dict[str, object] = {}
        self._cap = capacity
        self._step_i = 0
        self._created = time.monotonic()

    # -- recording (hot path: one lock, O(1)) ---------------------------
    def add(self, stage: str, seconds: float):
        with self._lock:
            s = self._series.get(stage)
            if s is None:
                s = self._series[stage] = _Series(self._cap)
            s.add(seconds)

    def incr(self, name: str, n: int = 1):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge(self, name: str, value: float):
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = _Gauge()
            g.observe(value)

    def set_info(self, name: str, value) -> None:
        """Attach a static (JSON-serializable) fact to the summary —
        e.g. the gradient-exchange plan under "comm"."""
        with self._lock:
            self._info[name] = value

    def mark_step(self, n: int = 1):
        """Timestamp `n` completed solver steps (throughput series).
        A fused K-step chunk lands K marks at the same instant — the
        steady-rate computation only cares about mark COUNT between
        first and last timestamp, so chunked and per-step runs report
        comparable steps/sec."""
        with self._lock:
            now = time.monotonic()
            for _ in range(max(1, n)):
                if len(self._steps) < self._cap:
                    self._steps.append(now)
                else:
                    self._steps[self._step_i] = now
                    self._step_i = (self._step_i + 1) % self._cap

    def add_chunk(self, n: int, seconds: float):
        """Fused-chunk accounting: one `scan_step` sample for the whole
        K-step dispatch, the recovered per-step device time (chunk/K)
        into the `step` series so per-step percentiles stay comparable
        with K=1 runs, and K step marks."""
        self.add("scan_step", seconds)
        per = seconds / max(1, n)
        for _ in range(max(1, n)):
            self.add("step", per)
        self.mark_step(n)

    # -- reading --------------------------------------------------------
    def get_counter(self, name: str) -> int:
        """One counter's current value (0 if never incremented) — the
        cheap point read for pollers (fleet bench, tests) that a full
        summary() would make O(all series)."""
        with self._lock:
            return self._counters.get(name, 0)

    def has_samples(self) -> bool:
        with self._lock:
            return bool(self._series or self._counters or self._steps
                        or self._info)

    def steady_steps_per_sec(self, skip: int = 5) -> Optional[float]:
        """Throughput over the step timestamps with the first `skip`
        (compile + cache warmup) steps discarded; None if too few."""
        with self._lock:
            if self._step_i:     # ring wrapped: chronological order
                ts = self._steps[self._step_i:] + self._steps[:self._step_i]
            else:
                ts = list(self._steps)
        ts = ts[skip:]
        if len(ts) < 2 or ts[-1] <= ts[0]:
            return None
        # count only marks strictly after the window start: a fused
        # chunk lands K marks at ONE timestamp, so (len-1)/span would
        # count the first chunk's remaining marks as work done inside
        # the window and overstate the rate; for per-step runs
        # (distinct timestamps) this is exactly (len-1)/span
        t0 = ts[0]
        n_after = sum(1 for t in ts if t > t0)
        return n_after / (ts[-1] - t0)

    def summary(self) -> dict:
        with self._lock:
            stages = {k: v.summary() for k, v in self._series.items()}
            counters = dict(self._counters)
            gauges = {k: v.summary() for k, v in self._gauges.items()}
            nsteps = len(self._steps)
            info = dict(self._info)
        out = {
            "stages": stages,
            "counters": counters,
            "queue_depths": gauges,
            "steps": nsteps,
            "uptime_s": round(time.monotonic() - self._created, 3),
        }
        if info:
            out["info"] = info
        sps = self.steady_steps_per_sec()
        if sps is not None:
            out["steady_steps_per_sec"] = round(sps, 3)
        return out

    def dump(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.summary(), f, indent=2, sort_keys=True)
            f.write("\n")
        return path

    def dump_atomic(self, path: str) -> str:
        """Summary via the fsync'd atomic-write path — readers (and a
        post-mortem after SIGKILL) only ever see a complete document."""
        from .utils.fsutils import atomic_write_local
        summary = self.summary()

        def _write(tmp):
            with open(tmp, "w") as f:
                json.dump(summary, f, indent=2, sort_keys=True)
                f.write("\n")

        atomic_write_local(path, _write)
        return path


def metrics_flush_s() -> float:
    """COS_METRICS_FLUSH_S: background-flush interval for the summary
    artifact; 0/unset = the historical dump-only-at-stop behavior."""
    from .utils.envutils import env_num
    return max(0.0, env_num("COS_METRICS_FLUSH_S", 0.0, strict=False))


class MetricsFlusher:
    """Background thread flushing a PipelineMetrics summary to disk
    every `interval_s` (the atomic-write path), so a SIGKILLed run
    leaves telemetry no older than one interval instead of nothing.
    `stop()` lands one final flush."""

    def __init__(self, metrics: PipelineMetrics, path: str,
                 interval_s: float):
        self.metrics = metrics
        self.path = path
        self.interval_s = max(0.05, float(interval_s))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.flushes = 0
        self.errors = 0

    def _flush_once(self) -> None:
        try:
            self.metrics.dump_atomic(self.path)
            self.flushes += 1
        except OSError:
            # a bad path/full disk must never take the run down; the
            # final stop() flush surfaces persistent failure via count
            self.errors += 1

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._flush_once()

    def start(self) -> "MetricsFlusher":
        assert self._thread is None, "flusher already started"
        self._thread = threading.Thread(target=self._loop,
                                        name="cos-metrics-flush",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        self._flush_once()


def maybe_start_flusher(metrics: PipelineMetrics,
                        output_dir: Optional[str],
                        filename: str = "metrics.json"
                        ) -> Optional[MetricsFlusher]:
    """Start the periodic flusher when COS_METRICS_FLUSH_S > 0 and an
    output directory exists to land `<output>/metrics.json` in."""
    interval = metrics_flush_s()
    if interval <= 0 or not output_dir:
        return None
    import os
    path = os.path.join(output_dir, filename)
    return MetricsFlusher(metrics, path, interval).start()
