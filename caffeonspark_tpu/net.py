"""Net compiler: NetParameter (+ NetState) → a functional JAX net.

TPU-native equivalent of caffe::Net construction inside
`CaffeNet<Dtype>::CaffeNet` (reference `caffe-distri/src/main/cpp/
CaffeNet.cpp:101-205`) and the per-phase layer filtering the driver does in
`Config.scala:73-86`.  Instead of a mutable layer graph, compilation
produces:

  * ``Net.init(key)``      → params pytree {layer: {blob: array}}
  * ``Net.apply(params, inputs, train, rng, state)`` → (blobs, new_state)
  * ``Net.loss(...)``      → weighted total loss + blobs (for jax.grad)

Everything in apply is traceable: one `jax.jit` covers the whole forward
(+backward via grad), letting XLA fuse elementwise chains into MXU matmul/
conv ops.  Layer inclusion rules (phase/stage/not_stage/level) follow
caffe's NetState::StateMeetsRule semantics used by lrcn_solver.prototxt's
train_state/test_state stages.
"""

from __future__ import annotations

import math
import os
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from .ops import layers as L
from .proto.caffe import (LayerParameter, NetParameter, NetState,
                          NetStateRule, Phase, TopBlobType)

Array = jax.Array
Params = Dict[str, Dict[str, Array]]


def state_meets_rule(rule: NetStateRule, state: NetState) -> bool:
    if rule.has("phase") and rule.phase != state.phase:
        return False
    if rule.has("min_level") and state.level < rule.min_level:
        return False
    if rule.has("max_level") and state.level > rule.max_level:
        return False
    stages = set(state.stage)
    for s in rule.stage:
        if s not in stages:
            return False
    for s in rule.not_stage:
        if s in stages:
            return False
    return True


def layer_included(lp: LayerParameter, state: NetState) -> bool:
    if lp.include:
        return any(state_meets_rule(r, state) for r in lp.include)
    if lp.exclude:
        return not any(state_meets_rule(r, state) for r in lp.exclude)
    return True


def _cos_top_shape(top, batch: int) -> Tuple[int, ...]:
    """Shape of one CoSData top (cos_data_layer.cpp:10-47 semantics)."""
    if top.transpose:
        # time-major (T, B) layout for RNN inputs
        return (int(top.channels), batch)
    axes = top.sample_num_axes
    t = top.type
    if t in (TopBlobType.ENCODED_IMAGE_WITH_DIM, TopBlobType.ENCODED_IMAGE,
             TopBlobType.RAW_IMAGE):
        c = int(top.out_channels or top.channels)
        h = int(top.out_height or top.height)
        w = int(top.out_width or top.width)
        if top.transform_param.crop_size:
            h = w = int(top.transform_param.crop_size)
        return (batch, c, h, w)
    if axes == 1:
        return (batch, int(top.channels))
    if axes == 0:
        return (batch,)
    return (batch, int(top.channels), int(top.height), int(top.width))


def _peek_db_dims(lp: LayerParameter) -> Tuple[int, int, int]:
    """First-record (C, H, W) of a Data layer's LMDB/LevelDB database;
    (3, 0, 0) when the database isn't readable at graph-build time
    (deploy nets parsed away from the data)."""
    from .proto.caffe import DBBackend, Datum
    try:
        from .data.source import _strip_scheme
        source = _strip_scheme(lp.data_param.source)
        if lp.data_param.backend == DBBackend.LEVELDB:
            from .data.leveldb_io import LevelDBReader as _Reader
        else:
            from .data.lmdb_io import LmdbReader as _Reader
        with _Reader(source) as r:
            for _k, v in r.items(None, None):
                d = Datum.from_binary(v)
                return int(d.channels), int(d.height), int(d.width)
    except Exception:
        pass
    return 3, 0, 0


def data_layer_input_specs(lp: LayerParameter) -> List[Tuple[str, Tuple[int, ...], str]]:
    """(blob_name, shape, kind) for each top of a data layer.
    kind ∈ {'data','label','int'} guides dtype selection downstream."""
    t = lp.type
    if t == "MemoryData":
        p = lp.memory_data_param
        b = int(p.batch_size)
        shape = (b, int(p.channels), int(p.height), int(p.width))
        if lp.transform_param.crop_size:
            cs = int(lp.transform_param.crop_size)
            shape = (b, int(p.channels), cs, cs)
        specs = [(lp.top[0], shape, "data")]
        if len(lp.top) > 1:
            specs.append((lp.top[1], (b,), "label"))
        return specs
    if t == "CoSData":
        p = lp.cos_data_param
        b = int(p.batch_size)
        # transpose tops are time-major (T, B): batch axis is 1
        return [(top.name, _cos_top_shape(top, b),
                 ("int" if top.type in (TopBlobType.INT,
                                        TopBlobType.INT_ARRAY) else "data")
                 + (":T" if top.transpose else ""))
                for top in p.top]
    if t == "Input":
        shapes = list(lp.input_param.shape)
        if len(shapes) == 1 and len(lp.top) > 1:
            shapes = shapes * len(lp.top)  # one shape shared by all tops
        if len(shapes) != len(lp.top):
            raise ValueError(f"Input layer {lp.name!r}: {len(shapes)} "
                             f"shapes for {len(lp.top)} tops")
        return [(name, tuple(int(d) for d in shp.dim), "data")
                for name, shp in zip(lp.top, shapes)]
    if t == "HDF5Data":
        # shapes live in the HDF5 files (hdf5_data_layer.cpp reads the
        # first listed file to size the tops) — probe it when the
        # source list is readable, else the caller must pass
        # input_shapes overrides (Net(..., input_shapes=...))
        import os
        p = lp.hdf5_data_param
        src = p.source
        if src and os.path.exists(src):
            from .data.hdf5 import hdf5_top_shapes
            shapes = hdf5_top_shapes(src, list(lp.top),
                                     int(p.batch_size))
            return [(name, shapes[name],
                     "label" if name == "label" else "data")
                    for name in lp.top]
        return [(name, (), "data") for name in lp.top]
    if t == "Data":
        p = lp.data_param
        b = int(p.batch_size)
        cs = int(p.crop_size or lp.transform_param.crop_size or 0)
        # Caffe's DataLayer reads the first Datum at LayerSetUp to size
        # its tops (data_layer.cpp); do the same so downstream layers
        # compile against the real geometry
        c, h, w = _peek_db_dims(lp)
        if cs:
            h = w = cs
        shape = (b, c, h or 1, w or 1)
        specs = [(lp.top[0], shape, "data")]
        if len(lp.top) > 1:
            specs.append((lp.top[1], (b,), "label"))
        return specs
    if t == "ImageData":
        # image_data_layer.cpp: (path label) list file; static TPU
        # shapes need new_height/new_width (or a crop) declared
        p = lp.image_data_param
        b = int(p.batch_size)
        c = 3 if p.is_color else 1
        cs = int(lp.transform_param.crop_size or 0)
        h = cs or int(p.new_height)
        w = cs or int(p.new_width)
        if not h or not w:
            raise ValueError(
                f"ImageData layer {lp.name!r}: set new_height/new_width "
                "(or transform_param.crop_size) — static shapes required")
        specs = [(lp.top[0], (b, c, h, w), "data")]
        if len(lp.top) > 1:
            specs.append((lp.top[1], (b,), "label"))
        return specs
    if t == "DummyData":
        p = lp.dummy_data_param
        out = []
        for i, name in enumerate(lp.top):
            if p.shape:
                shp = p.shape[min(i, len(p.shape) - 1)]
                out.append((name, tuple(int(d) for d in shp.dim), "data"))
            else:
                idx = min(i, len(p.num) - 1) if p.num else 0
                out.append((name, (int(p.num[idx]), int(p.channels[idx]),
                                   int(p.height[idx]), int(p.width[idx])),
                            "data"))
        return out
    raise NotImplementedError(f"data layer {t}")


class Net:
    """A compiled, phase-filtered network."""

    def __init__(self, net_param: NetParameter, state: Optional[NetState] = None,
                 input_shapes: Optional[Dict[str, Sequence[int]]] = None,
                 dtype=jnp.float32,
                 remat: Optional[Union[bool, str]] = None,
                 compute_dtype=None):
        self.net_param = net_param
        self.state = state or NetState(phase=Phase.TRAIN)
        self.name = net_param.name
        self.dtype = dtype
        # mixed precision: params stay `dtype` (f32 master weights for
        # optimizer updates) while the forward casts params+inputs to
        # `compute_dtype` (bf16 on the MXU); grads come back f32 via the
        # cast's transpose
        self.compute_dtype = compute_dtype or dtype
        # rematerialization: recompute layer activations in the backward
        # pass instead of storing them — trades MXU FLOPs for HBM
        # (jax.checkpoint per layer).  COS_REMAT=1 full per-layer remat
        # (max HBM savings, measured -21% on CaffeNet b256);
        # COS_REMAT=mxu keeps matmul/conv OUTPUTS and recomputes only
        # the cheap elementwise work — most of the memory win at a
        # fraction of the recompute tax, since the expensive MXU ops
        # never re-run
        if remat is None:
            import os
            remat = os.environ.get("COS_REMAT", "")
        if isinstance(remat, str):
            # env values and string args share one mapping; an unknown
            # value must error, not silently enable the WRONG remat
            # flavor (a truthy typo string used to read as full remat)
            try:
                remat = {"": False, "0": False, "false": False,
                         "off": False, "1": True, "full": True,
                         "true": True, "mxu": "mxu"}[remat.lower()]
            except KeyError:
                raise ValueError(
                    f"COS_REMAT={remat!r}: expected 0/1/full/mxu") \
                    from None
        self.remat = remat
        self.remat_policy = None
        if self.remat == "mxu":
            # save every MXU-op result (matmul AND conv — jax's
            # built-in checkpoint_dots covers only dot_general, which
            # misses convs entirely on a CNN), recompute just the
            # cheap VPU elementwise work
            def _mxu_saveable(prim, *_, **__):
                return prim.name in ("dot_general",
                                     "conv_general_dilated")
            self.remat_policy = _mxu_saveable

        self.layers: List[LayerParameter] = [
            lp for lp in net_param.layer if layer_included(lp, self.state)]

        # --- resolve net inputs ------------------------------------------
        self.input_specs: List[Tuple[str, Tuple[int, ...], str]] = []
        self.data_layers: List[LayerParameter] = []
        # legacy net-level inputs (deploy prototxts)
        if net_param.input:
            for i, name in enumerate(net_param.input):
                if net_param.input_shape:
                    shp = tuple(int(d)
                                for d in net_param.input_shape[i].dim)
                else:
                    shp = tuple(int(d)
                                for d in net_param.input_dim[4 * i:4 * i + 4])
                self.input_specs.append((name, shp, "data"))
        for lp in self.layers:
            if L.get_op(lp.type).is_data:
                self.data_layers.append(lp)
                specs = data_layer_input_specs(lp)
                if input_shapes:
                    specs = [(n, tuple(input_shapes.get(n, s)), k)
                             for (n, s, k) in specs]
                for n, s, _ in specs:
                    if len(s) == 0:
                        raise ValueError(
                            f"data layer {lp.name!r} ({lp.type}) top "
                            f"{n!r} has no shape in the prototxt — pass "
                            f"input_shapes={{'{n}': (...)}} to Net")
                self.input_specs.extend(specs)
        self.compute_layers = [lp for lp in self.layers
                               if not L.get_op(lp.type).is_data]

        # --- ReLU→LRN peephole (COS_FUSE_RELU_LRN=1, opt-in) -------------
        # XLA cannot fuse a producer into an opaque pallas call, so a
        # ReLU feeding the Pallas LRN kernel materializes its output as
        # the kernel's residual AND keeps the pre-activation live for
        # its own mask — one extra activation-sized HBM round trip per
        # stage in training.  Fused, the LRN kernel applies relu (and
        # its mask, in the VJP) in VMEM and the only residual is the
        # pre-activation.  Caveat (why opt-in): the relu top is no
        # longer a materialized blob — for an in-place relu the name
        # then holds the PRE-activation, so feature extraction of that
        # blob changes meaning.
        self.fused_relu_lrn: frozenset = frozenset()
        if os.environ.get("COS_FUSE_RELU_LRN") == "1":
            fused: set = set()
            self.compute_layers = self._fuse_relu_lrn(
                self.compute_layers, fused)
            self.fused_relu_lrn = frozenset(fused)

        # --- shape inference + param spec construction -------------------
        blob_shapes: Dict[str, Tuple[int, ...]] = {
            name: tuple(shape) for name, shape, _ in self.input_specs}
        self.param_layout: Dict[str, List[Tuple[str, Tuple[int, ...], object]]] = {}
        self._top_shapes: Dict[str, Dict[str, Tuple[int, ...]]] = {}
        for lp in self.compute_layers:
            op = L.get_op(lp.type)
            for b in lp.bottom:
                if b not in blob_shapes:
                    raise ValueError(
                        f"layer {lp.name!r} ({lp.type}) consumes unknown "
                        f"blob {b!r}; produced so far: "
                        f"{sorted(blob_shapes)}")
            bshapes = [blob_shapes[b] for b in lp.bottom]
            specs = [(n, tuple(int(x) for x in s), f)
                     for (n, s, f) in op.param_specs(lp, bshapes)]
            if specs:
                self.param_layout[lp.name] = specs
            # abstract evaluation for top shapes
            dummy_params = [jax.ShapeDtypeStruct(s, dtype)
                            for (_, s, _) in specs]
            dummy_bottoms = [jax.ShapeDtypeStruct(s, dtype) for s in bshapes]
            ctx = L.Ctx(train=self.state.phase == Phase.TRAIN,
                        rng=jax.random.key(0), layer_name=lp.name,
                        fused_relu_lrn=self.fused_relu_lrn)
            tops = jax.eval_shape(
                lambda p, b, lp=lp, op=op, ctx=ctx: op.apply(ctx, lp, p, b),
                dummy_params, dummy_bottoms)
            shaped = {}
            for name, tshape in zip(lp.top, tops):
                blob_shapes[name] = tuple(tshape.shape)
                shaped[name] = tuple(tshape.shape)
            self._top_shapes[lp.name] = shaped
        self.blob_shapes = blob_shapes

        # --- net outputs: tops never consumed ----------------------------
        consumed = {b for lp in self.compute_layers for b in lp.bottom}
        produced: List[str] = [n for n, _, _ in self.input_specs]
        for lp in self.compute_layers:
            for t in lp.top:
                if t not in produced:
                    produced.append(t)
        # in-place layers re-produce their bottom; a blob is an output if no
        # layer consumes it — approximate Caffe: top not in consumed
        self.output_blobs = [n for n in produced if n not in consumed]
        # loss weights per top
        self.loss_weights: Dict[str, float] = {}
        for lp in self.compute_layers:
            op = L.get_op(lp.type)
            for i, t in enumerate(lp.top):
                if i < len(lp.loss_weight):
                    w = float(lp.loss_weight[i])
                elif op.is_loss:
                    w = 1.0
                else:
                    w = 0.0
                if w:
                    self.loss_weights[t] = w

    # ------------------------------------------------------------------
    def _fuse_relu_lrn(self, layers: List[LayerParameter], fused: set
                       ) -> List[LayerParameter]:
        """Replace eligible [ReLU, LRN] pairs with one LRN layer whose
        op applies relu in-kernel (see __init__).  Eligible: plain relu
        (negative_slope 0, no loss weight, 1 bottom / 1 top) whose top
        is consumed by exactly one later layer, an ACROSS_CHANNELS LRN.
        The LRN entry is a deep copy (the source NetParameter may build
        other Nets); its name is added to `fused` (becomes
        self.fused_relu_lrn, which Net.apply threads to the op through
        Ctx)."""
        from .proto.caffe import NormRegion
        out: List[Optional[LayerParameter]] = list(layers)
        for i, r in enumerate(out):
            if r is None or r.type != "ReLU":
                continue
            if len(r.bottom) != 1 or len(r.top) != 1:
                continue
            if float(getattr(r.relu_param, "negative_slope", 0.0) or 0.0):
                continue
            if any(float(w) for w in r.loss_weight):
                continue
            rtop = r.top[0]
            consumers = [(j, lp) for j, lp in enumerate(out)
                         if lp is not None and j > i and rtop in lp.bottom]
            if len(consumers) != 1:
                continue
            j, nl = consumers[0]
            if (nl.type != "LRN" or len(nl.bottom) != 1
                    or nl.lrn_param.norm_region
                    != NormRegion.ACROSS_CHANNELS):
                continue
            fused_lp = LayerParameter.from_binary(nl.to_binary())
            fused_lp.bottom = [r.bottom[0]]
            out[j] = fused_lp
            out[i] = None
            fused.add(nl.name)
        return [lp for lp in out if lp is not None]

    # ------------------------------------------------------------------
    def init(self, key: Array) -> Params:
        """Initialize all learnable blobs (filler semantics)."""
        from .ops.fillers import fill
        from .ops.layers import stable_hash
        params: Params = {}
        for lname, specs in self.param_layout.items():
            lkey = jax.random.fold_in(key, stable_hash(lname))
            blobs = {}
            for i, (bname, shape, filler) in enumerate(specs):
                blobs[bname] = fill(jax.random.fold_in(lkey, i), filler,
                                    shape, self.dtype)
            params[lname] = blobs
        return params

    def input_names(self) -> List[str]:
        return [n for n, _, _ in self.input_specs]

    def make_dummy_inputs(self, batch_override: Optional[int] = None
                          ) -> Dict[str, Array]:
        out = {}
        for name, shape, kind in self.input_specs:
            if batch_override is not None:
                # time-major (":T") tops carry batch on axis 1, not 0
                ax = 1 if kind.endswith(":T") else 0
                shape = tuple(batch_override if i == ax else d
                              for i, d in enumerate(shape))
            out[name] = jnp.zeros(shape, self.dtype)
        return out

    # ------------------------------------------------------------------
    def apply(self, params: Params, inputs: Dict[str, Array], *,
              train: Optional[bool] = None, rng: Optional[Array] = None,
              net_state: Optional[Dict] = None
              ) -> Tuple[Dict[str, Array], Dict]:
        """Forward pass. Returns (all blobs, updated_param_blobs).

        The second value maps layer name → [new blob arrays] for layers
        that update their own param blobs during the forward pass
        (BatchNorm running stats).  `Solver.train_step` merges it back
        into params with `merge_forward_state`; stat blobs are pinned to
        lr_mult = decay_mult = 0 so the optimizer never touches them."""
        if train is None:
            train = self.state.phase == Phase.TRAIN
        blobs: Dict[str, Array] = dict(inputs)
        ctx = L.Ctx(train=train, rng=rng,
                    state_in=net_state or {}, state_out={},
                    fused_relu_lrn=self.fused_relu_lrn)
        cast = (self.compute_dtype != self.dtype)
        for lp in self.compute_layers:
            op = L.get_op(lp.type)
            ctx.layer_name = lp.name
            lparams = []
            if lp.name in self.param_layout:
                pd = params[lp.name]
                lparams = [pd[bname]
                           for bname, _, _ in self.param_layout[lp.name]]
                if cast and not op.f32_stats:
                    lparams = [p.astype(self.compute_dtype)
                               for p in lparams]
            bottoms = [blobs[b] for b in lp.bottom]
            if cast and not op.f32_stats:
                # stat layers (BatchNorm) also keep their INPUT at full
                # precision: E[x²]−E[x]² cancels catastrophically in
                # bf16 for unnormalized activations
                bottoms = [b.astype(self.compute_dtype)
                           if jnp.issubdtype(b.dtype, jnp.floating)
                           and b.dtype != self.compute_dtype else b
                           for b in bottoms]
            elif cast and op.f32_stats:
                bottoms = [b.astype(self.dtype)
                           if jnp.issubdtype(b.dtype, jnp.floating)
                           and b.dtype != self.dtype else b
                           for b in bottoms]
            if self.remat and train and lparams \
                    and not op.f32_stats:
                # only parameterized layers are checkpointed — wrapping
                # elementwise ops would just block XLA fusion; BatchNorm
                # is excluded because its running-stat side channel
                # (ctx.state_out) must not cross the remat boundary
                kw = ({"policy": self.remat_policy}
                      if self.remat_policy is not None else {})
                fn = jax.checkpoint(
                    lambda p, b, op=op, lp=lp, ctx=ctx:
                    op.apply(ctx, lp, p, b), **kw)
                tops = fn(lparams, bottoms)
            else:
                tops = op.apply(ctx, lp, lparams, bottoms)
            for name, val in zip(lp.top, tops):
                blobs[name] = val
        return blobs, ctx.state_out

    def loss(self, params: Params, inputs: Dict[str, Array], *,
             train: bool = True, rng: Optional[Array] = None,
             net_state: Optional[Dict] = None
             ) -> Tuple[Array, Tuple[Dict[str, Array], Dict]]:
        """Total weighted loss (for jax.value_and_grad(has_aux=True))."""
        blobs, new_state = self.apply(params, inputs, train=train, rng=rng,
                                      net_state=net_state)
        # the scalar loss ACCUMULATES in f32 regardless of compute dtype
        # (a bf16 running sum over a large blob drops addends)
        total = jnp.zeros((), jnp.float32)
        for name, w in self.loss_weights.items():
            total = total + w * jnp.sum(blobs[name],
                                        dtype=jnp.float32)
        return total, (blobs, new_state)

    def merge_forward_state(self, params: Params,
                            forward_state: Dict[str, List[Array]]) -> Params:
        """Overwrite self-updating param blobs (BatchNorm stats) with the
        values produced by the last forward pass."""
        if not forward_state:
            return params
        out = {ln: dict(bl) for ln, bl in params.items()}
        for lname, blobs in forward_state.items():
            if lname not in self.param_layout:
                continue   # side-channel keys (LSTM hidden, HDF5Output)
            for (bname, _, _), arr in zip(self.param_layout[lname], blobs):
                out[lname][bname] = arr
        return out

    def stat_param_layers(self) -> List[str]:
        """Layers whose param blobs are running statistics, not weights
        (op-level f32_stats flag, e.g. BatchNorm)."""
        return [lp.name for lp in self.compute_layers
                if L.get_op(lp.type).f32_stats]

    def num_params(self, params: Optional[Params] = None) -> int:
        if params is not None:
            return sum(int(x.size) for lb in params.values()
                       for x in lb.values())
        return sum(math.prod(s) for specs in self.param_layout.values()
                   for (_, s, _) in specs)
