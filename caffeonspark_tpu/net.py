"""Net compiler: NetParameter (+ NetState) → a functional JAX net.

TPU-native equivalent of caffe::Net construction inside
`CaffeNet<Dtype>::CaffeNet` (reference `caffe-distri/src/main/cpp/
CaffeNet.cpp:101-205`) and the per-phase layer filtering the driver does in
`Config.scala:73-86`.  Instead of a mutable layer graph, compilation
produces:

  * ``Net.init(key)``      → params pytree {layer: {blob: array}}
  * ``Net.apply(params, inputs, train, rng, state)`` → (blobs, new_state)
  * ``Net.loss(...)``      → weighted total loss + blobs (for jax.grad)

Everything in apply is traceable: one `jax.jit` covers the whole forward
(+backward via grad), letting XLA fuse elementwise chains into MXU matmul/
conv ops.  Layer inclusion rules (phase/stage/not_stage/level) follow
caffe's NetState::StateMeetsRule semantics used by lrcn_solver.prototxt's
train_state/test_state stages.
"""

from __future__ import annotations

import math
import os
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from .ops import layers as L
from .proto.caffe import (LayerParameter, NetParameter, NetState,
                          NetStateRule, Phase, TopBlobType)

Array = jax.Array
Params = Dict[str, Dict[str, Array]]


def state_meets_rule(rule: NetStateRule, state: NetState) -> bool:
    if rule.has("phase") and rule.phase != state.phase:
        return False
    if rule.has("min_level") and state.level < rule.min_level:
        return False
    if rule.has("max_level") and state.level > rule.max_level:
        return False
    stages = set(state.stage)
    for s in rule.stage:
        if s not in stages:
            return False
    for s in rule.not_stage:
        if s in stages:
            return False
    return True


def layer_included(lp: LayerParameter, state: NetState) -> bool:
    if lp.include:
        return any(state_meets_rule(r, state) for r in lp.include)
    if lp.exclude:
        return not any(state_meets_rule(r, state) for r in lp.exclude)
    return True


def _cos_top_shape(top, batch: int) -> Tuple[int, ...]:
    """Shape of one CoSData top (cos_data_layer.cpp:10-47 semantics)."""
    if top.transpose:
        # time-major (T, B) layout for RNN inputs
        return (int(top.channels), batch)
    axes = top.sample_num_axes
    t = top.type
    if t in (TopBlobType.ENCODED_IMAGE_WITH_DIM, TopBlobType.ENCODED_IMAGE,
             TopBlobType.RAW_IMAGE):
        c = int(top.out_channels or top.channels)
        h = int(top.out_height or top.height)
        w = int(top.out_width or top.width)
        if top.transform_param.crop_size:
            h = w = int(top.transform_param.crop_size)
        return (batch, c, h, w)
    if axes == 1:
        return (batch, int(top.channels))
    if axes == 0:
        return (batch,)
    return (batch, int(top.channels), int(top.height), int(top.width))


def _peek_db_dims(lp: LayerParameter) -> Tuple[int, int, int]:
    """First-record (C, H, W) of a Data layer's LMDB/LevelDB database;
    (3, 0, 0) when the database isn't readable at graph-build time
    (deploy nets parsed away from the data)."""
    from .proto.caffe import DBBackend, Datum
    try:
        from .data.source import _strip_scheme
        source = _strip_scheme(lp.data_param.source)
        if lp.data_param.backend == DBBackend.LEVELDB:
            from .data.leveldb_io import LevelDBReader as _Reader
        else:
            from .data.lmdb_io import LmdbReader as _Reader
        with _Reader(source) as r:
            for _k, v in r.items(None, None):
                d = Datum.from_binary(v)
                return int(d.channels), int(d.height), int(d.width)
    except Exception:
        pass
    return 3, 0, 0


def data_layer_input_specs(lp: LayerParameter) -> List[Tuple[str, Tuple[int, ...], str]]:
    """(blob_name, shape, kind) for each top of a data layer.
    kind ∈ {'data','label','int'} guides dtype selection downstream."""
    t = lp.type
    if t == "MemoryData":
        p = lp.memory_data_param
        b = int(p.batch_size)
        shape = (b, int(p.channels), int(p.height), int(p.width))
        if lp.transform_param.crop_size:
            cs = int(lp.transform_param.crop_size)
            shape = (b, int(p.channels), cs, cs)
        specs = [(lp.top[0], shape, "data")]
        if len(lp.top) > 1:
            specs.append((lp.top[1], (b,), "label"))
        return specs
    if t == "CoSData":
        p = lp.cos_data_param
        b = int(p.batch_size)
        # transpose tops are time-major (T, B): batch axis is 1
        return [(top.name, _cos_top_shape(top, b),
                 ("int" if top.type in (TopBlobType.INT,
                                        TopBlobType.INT_ARRAY) else "data")
                 + (":T" if top.transpose else ""))
                for top in p.top]
    if t == "Input":
        shapes = list(lp.input_param.shape)
        if len(shapes) == 1 and len(lp.top) > 1:
            shapes = shapes * len(lp.top)  # one shape shared by all tops
        if len(shapes) != len(lp.top):
            raise ValueError(f"Input layer {lp.name!r}: {len(shapes)} "
                             f"shapes for {len(lp.top)} tops")
        return [(name, tuple(int(d) for d in shp.dim), "data")
                for name, shp in zip(lp.top, shapes)]
    if t == "HDF5Data":
        # shapes live in the HDF5 files (hdf5_data_layer.cpp reads the
        # first listed file to size the tops) — probe it when the
        # source list is readable, else the caller must pass
        # input_shapes overrides (Net(..., input_shapes=...))
        import os
        p = lp.hdf5_data_param
        src = p.source
        if src and os.path.exists(src):
            from .data.hdf5 import hdf5_top_shapes
            shapes = hdf5_top_shapes(src, list(lp.top),
                                     int(p.batch_size))
            return [(name, shapes[name],
                     "label" if name == "label" else "data")
                    for name in lp.top]
        return [(name, (), "data") for name in lp.top]
    if t == "Data":
        p = lp.data_param
        b = int(p.batch_size)
        cs = int(p.crop_size or lp.transform_param.crop_size or 0)
        # Caffe's DataLayer reads the first Datum at LayerSetUp to size
        # its tops (data_layer.cpp); do the same so downstream layers
        # compile against the real geometry
        c, h, w = _peek_db_dims(lp)
        if cs:
            h = w = cs
        shape = (b, c, h or 1, w or 1)
        specs = [(lp.top[0], shape, "data")]
        if len(lp.top) > 1:
            specs.append((lp.top[1], (b,), "label"))
        return specs
    if t == "ImageData":
        # image_data_layer.cpp: (path label) list file; static TPU
        # shapes need new_height/new_width (or a crop) declared
        p = lp.image_data_param
        b = int(p.batch_size)
        c = 3 if p.is_color else 1
        cs = int(lp.transform_param.crop_size or 0)
        h = cs or int(p.new_height)
        w = cs or int(p.new_width)
        if not h or not w:
            raise ValueError(
                f"ImageData layer {lp.name!r}: set new_height/new_width "
                "(or transform_param.crop_size) — static shapes required")
        specs = [(lp.top[0], (b, c, h, w), "data")]
        if len(lp.top) > 1:
            specs.append((lp.top[1], (b,), "label"))
        return specs
    if t == "DummyData":
        p = lp.dummy_data_param
        out = []
        for i, name in enumerate(lp.top):
            if p.shape:
                shp = p.shape[min(i, len(p.shape) - 1)]
                out.append((name, tuple(int(d) for d in shp.dim), "data"))
            else:
                idx = min(i, len(p.num) - 1) if p.num else 0
                out.append((name, (int(p.num[idx]), int(p.channels[idx]),
                                   int(p.height[idx]), int(p.width[idx])),
                            "data"))
        return out
    raise NotImplementedError(f"data layer {t}")


def fusable_relu_for_lrn(layers: Sequence[LayerParameter],
                         lrn_lp: LayerParameter
                         ) -> Optional[LayerParameter]:
    """THE ReLU→LRN fusion-eligibility rule, as a predicate: the ReLU
    layer `_fuse_relu_lrn` would absorb into `lrn_lp`, or None.  One
    copy — the peephole applies it, and the autotuner's variant
    enumeration (`ops/autotune.py`) and the roofline byte model
    (`analysis/roofline.py`) consult the SAME rule, so neither can
    enumerate or credit a fusion the build refuses.

    Eligible: `lrn_lp` is a 1-bottom ACROSS_CHANNELS LRN whose
    bottom's last producer is a plain ReLU (negative_slope 0, no loss
    weight, 1 bottom / 1 top) consumed by nothing but the LRN."""
    from .proto.caffe import NormRegion
    if (lrn_lp.type != "LRN" or len(lrn_lp.bottom) != 1
            or lrn_lp.lrn_param.norm_region
            != NormRegion.ACROSS_CHANNELS):
        return None
    prod, pi = None, -1
    found = False
    for j, l2 in enumerate(layers):
        if l2 is lrn_lp:
            found = True
            break
        if lrn_lp.bottom[0] in l2.top:
            prod, pi = l2, j
    if not found or prod is None or prod.type != "ReLU":
        return None
    if len(prod.bottom) != 1 or len(prod.top) != 1:
        return None
    if float(getattr(prod.relu_param, "negative_slope", 0.0) or 0.0):
        return None
    if any(float(w) for w in prod.loss_weight):
        return None
    consumers = [l2 for j, l2 in enumerate(layers)
                 if j > pi and prod.top[0] in l2.bottom]
    if consumers != [lrn_lp]:
        return None
    return prod


def prefuse_conv_bias_eligible(layers: Sequence[LayerParameter],
                               lrn_lp: LayerParameter,
                               relu_lp: LayerParameter) -> bool:
    """PRE-fuse mirror of `_fuse_conv_bias`'s rule (which runs on the
    post-fuse layer list): would the conv feeding `relu_lp` get its
    bias deferred into `lrn_lp` once the relu is fused away?  True
    when that producer is a bias_term Convolution whose top feeds
    nothing but the relu chain (for an in-place relu, the LRN also
    reads the name — that IS the chain)."""
    conv, ci = None, -1
    for j, l2 in enumerate(layers):
        if l2 is relu_lp:
            break
        if relu_lp.bottom[0] in l2.top:
            conv, ci = l2, j
    if (conv is None or conv.type != "Convolution"
            or not conv.convolution_param.bias_term):
        return False
    others = [l2 for j, l2 in enumerate(layers)
              if j > ci and conv.top[0] in l2.bottom
              and l2 is not relu_lp]
    return others in ([], [lrn_lp])


class Net:
    """A compiled, phase-filtered network."""

    def __init__(self, net_param: NetParameter, state: Optional[NetState] = None,
                 input_shapes: Optional[Dict[str, Sequence[int]]] = None,
                 dtype=jnp.float32,
                 remat: Optional[Union[bool, str]] = None,
                 compute_dtype=None,
                 autotune: Union[None, bool, str, dict] = None):
        self.net_param = net_param
        self.state = state or NetState(phase=Phase.TRAIN)
        self.name = net_param.name
        self.dtype = dtype
        # mixed precision: params stay `dtype` (f32 master weights for
        # optimizer updates) while the forward casts params+inputs to
        # `compute_dtype` (bf16 on the MXU); grads come back f32 via the
        # cast's transpose
        self.compute_dtype = compute_dtype or dtype
        # rematerialization: recompute layer activations in the backward
        # pass instead of storing them — trades MXU FLOPs for HBM
        # (jax.checkpoint per layer).  COS_REMAT=1 full per-layer remat
        # (max HBM savings, measured -21% on CaffeNet b256);
        # COS_REMAT=mxu keeps matmul/conv OUTPUTS and recomputes only
        # the cheap elementwise work — most of the memory win at a
        # fraction of the recompute tax, since the expensive MXU ops
        # never re-run
        if remat is None:
            import os
            remat = os.environ.get("COS_REMAT", "")
        if isinstance(remat, str):
            # env values and string args share one mapping; an unknown
            # value must error, not silently enable the WRONG remat
            # flavor (a truthy typo string used to read as full remat)
            try:
                remat = {"": False, "0": False, "false": False,
                         "off": False, "1": True, "full": True,
                         "true": True, "mxu": "mxu"}[remat.lower()]
            except KeyError:
                raise ValueError(
                    f"COS_REMAT={remat!r}: expected 0/1/full/mxu") \
                    from None
        self.remat = remat
        self.remat_policy = None
        if self.remat == "mxu":
            # save every MXU-op result (matmul AND conv — jax's
            # built-in checkpoint_dots covers only dot_general, which
            # misses convs entirely on a CNN), recompute just the
            # cheap VPU elementwise work
            def _mxu_saveable(prim, *_, **__):
                return prim.name in ("dot_general",
                                     "conv_general_dilated")
            self.remat_policy = _mxu_saveable

        self.layers: List[LayerParameter] = [
            lp for lp in net_param.layer if layer_included(lp, self.state)]

        # --- resolve net inputs ------------------------------------------
        self.input_specs: List[Tuple[str, Tuple[int, ...], str]] = []
        self.data_layers: List[LayerParameter] = []
        # legacy net-level inputs (deploy prototxts)
        if net_param.input:
            for i, name in enumerate(net_param.input):
                if net_param.input_shape:
                    shp = tuple(int(d)
                                for d in net_param.input_shape[i].dim)
                else:
                    shp = tuple(int(d)
                                for d in net_param.input_dim[4 * i:4 * i + 4])
                self.input_specs.append((name, shp, "data"))
        for lp in self.layers:
            if L.get_op(lp.type).is_data:
                self.data_layers.append(lp)
                specs = data_layer_input_specs(lp)
                if input_shapes:
                    specs = [(n, tuple(input_shapes.get(n, s)), k)
                             for (n, s, k) in specs]
                for n, s, _ in specs:
                    if len(s) == 0:
                        raise ValueError(
                            f"data layer {lp.name!r} ({lp.type}) top "
                            f"{n!r} has no shape in the prototxt — pass "
                            f"input_shapes={{'{n}': (...)}} to Net")
                self.input_specs.extend(specs)
        self.compute_layers = [lp for lp in self.layers
                               if not L.get_op(lp.type).is_data]

        # --- autotune plan (COS_AUTOTUNE, resolved ONCE here — never at
        # trace time; COS003 discipline).  None/unset/"0" is INERT:
        # no plan, no per-layer variants, byte-identical construction.
        # `autotune` arg: False forces inert (the tuner's candidate
        # nets), a dict is an explicit plan, a str a plan path, None
        # defers to the env.
        self.autotune_plan: Optional[dict] = None
        self.layer_variants: Dict[str, dict] = {}
        if autotune is not False:
            from .ops.autotune import dtype_policy_str, resolve_plan
            self.autotune_plan, self.layer_variants = resolve_plan(
                net_param, self.state, autotune,
                dtype_policy=dtype_policy_str(self.dtype,
                                              self.compute_dtype))

        # --- ReLU→LRN peephole (COS_FUSE_RELU_LRN=1, opt-in; also
        # requested per-layer by the autotune plan) -----------------------
        # XLA cannot fuse a producer into an opaque pallas call, so a
        # ReLU feeding the Pallas LRN kernel materializes its output as
        # the kernel's residual AND keeps the pre-activation live for
        # its own mask — one extra activation-sized HBM round trip per
        # stage in training.  Fused, the LRN kernel applies relu (and
        # its mask, in the VJP) in VMEM and the only residual is the
        # pre-activation.  Caveat (why opt-in): the relu top is no
        # longer a materialized blob — for an in-place relu the name
        # then holds the PRE-activation, so feature extraction of that
        # blob changes meaning.
        # COS_FUSE_BIAS_RELU_LRN=1 (or a plan variant fuse=bias_relu)
        # generalizes the epilogue one producer further: the conv's
        # bias add joins relu+lrn in the kernel, the conv emits its RAW
        # matmul output, and d_bias is recovered exactly from the
        # kernel's dx (ops/pallas_kernels.bias_relu_lrn_across_channels).
        self.fused_relu_lrn: frozenset = frozenset()
        self.fused_bias_lrn: Dict[str, str] = {}      # lrn → conv
        plan_fuse = {n for n, v in self.layer_variants.items()
                     if v.get("fuse") in ("relu", "bias_relu")}
        plan_deny = frozenset(n for n, v in self.layer_variants.items()
                              if v.get("fuse") == "none")
        env_fuse_all = os.environ.get("COS_FUSE_RELU_LRN") == "1"
        env_bias = os.environ.get("COS_FUSE_BIAS_RELU_LRN") == "1"
        if env_fuse_all or env_bias or plan_fuse:
            fused: set = set()
            self.compute_layers = self._fuse_relu_lrn(
                self.compute_layers, fused,
                want=None if (env_fuse_all or env_bias) else plan_fuse,
                deny=plan_deny)
            self.fused_relu_lrn = frozenset(fused)
            bias_want = (None if env_bias else
                         {n for n, v in self.layer_variants.items()
                          if v.get("fuse") == "bias_relu"})
            if env_bias or bias_want:
                self.fused_bias_lrn = self._fuse_conv_bias(bias_want)
        self._bias_lrn_set = frozenset(self.fused_bias_lrn)
        self._defer_bias = frozenset(self.fused_bias_lrn.values())
        self._validate_variants()

        # --- shape inference + param spec construction -------------------
        blob_shapes: Dict[str, Tuple[int, ...]] = {
            name: tuple(shape) for name, shape, _ in self.input_specs}
        self.param_layout: Dict[str, List[Tuple[str, Tuple[int, ...], object]]] = {}
        self._top_shapes: Dict[str, Dict[str, Tuple[int, ...]]] = {}
        for lp in self.compute_layers:
            op = L.get_op(lp.type)
            for b in lp.bottom:
                if b not in blob_shapes:
                    raise ValueError(
                        f"layer {lp.name!r} ({lp.type}) consumes unknown "
                        f"blob {b!r}; produced so far: "
                        f"{sorted(blob_shapes)}")
            bshapes = [blob_shapes[b] for b in lp.bottom]
            specs = [(n, tuple(int(x) for x in s), f)
                     for (n, s, f) in op.param_specs(lp, bshapes)]
            if specs:
                self.param_layout[lp.name] = specs
            # abstract evaluation for top shapes
            dummy_params = [jax.ShapeDtypeStruct(s, dtype)
                            for (_, s, _) in specs]
            if lp.name in self.fused_bias_lrn:
                # the bias-fused LRN consumes the producing conv's bias
                # as params[0] (the conv is earlier in topo order, so
                # its layout is already known)
                conv = self.fused_bias_lrn[lp.name]
                bshape = next(s for (n2, s, _) in
                              self.param_layout[conv] if n2 == "bias")
                dummy_params = [jax.ShapeDtypeStruct(bshape, dtype)] \
                    + dummy_params
            dummy_bottoms = [jax.ShapeDtypeStruct(s, dtype) for s in bshapes]
            ctx = L.Ctx(train=self.state.phase == Phase.TRAIN,
                        rng=jax.random.key(0), layer_name=lp.name,
                        fused_relu_lrn=self.fused_relu_lrn,
                        variant=self.layer_variants.get(lp.name),
                        defer_bias=self._defer_bias,
                        bias_lrn=self._bias_lrn_set)
            tops = jax.eval_shape(
                lambda p, b, lp=lp, op=op, ctx=ctx: op.apply(ctx, lp, p, b),
                dummy_params, dummy_bottoms)
            shaped = {}
            for name, tshape in zip(lp.top, tops):
                blob_shapes[name] = tuple(tshape.shape)
                shaped[name] = tuple(tshape.shape)
            self._top_shapes[lp.name] = shaped
        self.blob_shapes = blob_shapes

        # --- net outputs: tops never consumed ----------------------------
        consumed = {b for lp in self.compute_layers for b in lp.bottom}
        produced: List[str] = [n for n, _, _ in self.input_specs]
        for lp in self.compute_layers:
            for t in lp.top:
                if t not in produced:
                    produced.append(t)
        # in-place layers re-produce their bottom; a blob is an output if no
        # layer consumes it — approximate Caffe: top not in consumed
        self.output_blobs = [n for n in produced if n not in consumed]
        # loss weights per top
        self.loss_weights: Dict[str, float] = {}
        for lp in self.compute_layers:
            op = L.get_op(lp.type)
            for i, t in enumerate(lp.top):
                if i < len(lp.loss_weight):
                    w = float(lp.loss_weight[i])
                elif op.is_loss:
                    w = 1.0
                else:
                    w = 0.0
                if w:
                    self.loss_weights[t] = w

    # ------------------------------------------------------------------
    def _fuse_relu_lrn(self, layers: List[LayerParameter], fused: set,
                       want: Optional[set] = None,
                       deny: frozenset = frozenset()
                       ) -> List[LayerParameter]:
        """Replace eligible [ReLU, LRN] pairs with one LRN layer whose
        op applies relu in-kernel (see __init__).  Eligibility is the
        module-level `fusable_relu_for_lrn` predicate — the ONE copy
        the autotuner and roofline model also consult.  The LRN entry
        is a deep copy (the source NetParameter may build other Nets);
        its name is added to `fused` (becomes self.fused_relu_lrn,
        which Net.apply threads to the op through Ctx).  `want`
        restricts fusion to the named LRN layers (the autotune plan's
        per-layer request; None = every eligible pair, the env-knob
        behavior); `deny` always blocks the named LRNs (a plan
        fuse=none beats the env knob)."""
        out: List[LayerParameter] = list(layers)
        i = 0
        while i < len(out):
            nl = out[i]
            if nl.type != "LRN" or nl.name in deny \
                    or (want is not None and nl.name not in want):
                i += 1
                continue
            r = fusable_relu_for_lrn(out, nl)
            if r is None:
                i += 1
                continue
            fused_lp = LayerParameter.from_binary(nl.to_binary())
            fused_lp.bottom = [r.bottom[0]]
            out[i] = fused_lp
            ri = next(j for j, l2 in enumerate(out) if l2 is r)
            del out[ri]            # ri < i: the producer sits earlier,
            #                        so out[i-1] is now the fused LRN
            #                        and out[i] the next layer to scan
            fused.add(nl.name)
        return out

    # ------------------------------------------------------------------
    def _fuse_conv_bias(self, want: Optional[set]) -> Dict[str, str]:
        """Second stem-peephole pass: for relu-fused LRN layers (their
        bottom is now the conv's raw top), defer the producing conv's
        bias add into the LRN kernel's epilogue.  Eligible: the LRN's
        single bottom is produced by a bias_term Convolution whose top
        is consumed by NO other layer.  Returns {lrn_name: conv_name};
        Net.apply routes the conv's bias blob to the LRN as params[0]
        and tells the conv op to skip its own add (Ctx.defer_bias) —
        gradients still land on the conv's bias through the fused
        kernel's VJP.  `want` restricts to the named LRNs (autotune
        plan); None = every eligible fused pair (the env knob).

        Caveat (the relu peephole's, one producer deeper — why this
        too is opt-in): the conv's top name now holds the RAW matmul
        output, so feature-extracting that blob returns UNBIASED
        activations.  The layer-consumer check above cannot see the
        extraction surface (-features names arbitrary blobs at run
        time); don't enable bias fusion on nets whose conv stems feed
        feature extraction."""
        out: Dict[str, str] = {}
        by_top: Dict[str, LayerParameter] = {}
        for lp in self.compute_layers:
            for t in lp.top:
                by_top[t] = lp
        for lp in self.compute_layers:
            if lp.name not in self.fused_relu_lrn:
                continue
            if want is not None and lp.name not in want:
                continue
            src = by_top.get(lp.bottom[0])
            if (src is None or src.type != "Convolution"
                    or not src.convolution_param.bias_term):
                continue
            consumers = [o for o in self.compute_layers
                         if o is not lp and src.top[0] in o.bottom]
            if consumers:
                continue     # someone else needs the biased activation
            out[lp.name] = src.name
        return out

    # ------------------------------------------------------------------
    def _validate_variants(self) -> None:
        """Drop plan entries that cannot apply to THIS net: unknown
        layer names (pruned relus, other phases), int8 on a TRAIN-phase
        net (the quantized matmul is forward-only serving), and
        type-mismatched knobs.  Dropping with a log line — never
        erroring — keeps one plan applicable to the train/test net pair
        it was tuned against."""
        self._variant_dtype: Dict[str, object] = {}
        if not self.layer_variants:
            return
        import logging
        log = logging.getLogger(__name__)
        by_name = {lp.name: lp.type for lp in self.compute_layers}
        train = self.state.phase == Phase.TRAIN
        keep: Dict[str, dict] = {}
        for name, v in self.layer_variants.items():
            t = by_name.get(name)
            if t is None:
                continue                 # fused-away or other-phase layer
            v = dict(v)
            if v.get("int8") and (train or t != "InnerProduct"):
                log.warning("autotune: dropping int8 variant on %s "
                            "(%s, train=%s) — serving InnerProduct only",
                            name, t, train)
                v.pop("int8")
            if v.get("layout") and t != "Convolution":
                v.pop("layout")
            if v.get("attention") and t != "MultiHeadAttention":
                v.pop("attention")
            if v.get("fuse") and t != "LRN":
                v.pop("fuse")
            # reconcile fuse with what the peephole ACTUALLY did:
            # info.autotune publishes "the variants applied to THIS
            # net", so a refused fusion must not be reported as
            # applied (a bias_relu the bias pass refused downgrades
            # to the relu fusion that did land, or disappears)
            fuse = v.get("fuse")
            if fuse == "bias_relu" and name not in self.fused_bias_lrn:
                fuse = "relu" if name in self.fused_relu_lrn else None
            elif fuse == "relu" and name not in self.fused_relu_lrn:
                fuse = None
            if fuse != v.get("fuse") and v.get("fuse") != "none":
                log.warning(
                    "autotune: fuse=%s on %s not applied (peephole "
                    "eligibility) — reporting %s", v.get("fuse"), name,
                    fuse or "unfused")
                if fuse is None:
                    v.pop("fuse")
                else:
                    v["fuse"] = fuse
            if v:
                keep[name] = v
        self.layer_variants = keep
        self._variant_dtype = {
            n: jnp.dtype(v["dtype"]) for n, v in keep.items()
            if v.get("dtype")}

    def autotune_info(self) -> dict:
        """The self-describing `info.autotune` block every metrics
        artifact carries (like info.comm / info.sync): {"active":
        False} when COS_AUTOTUNE is unset, else the plan's key, source,
        and the per-layer variants actually applied to THIS net."""
        if not self.autotune_plan:
            return {"active": False}
        p = self.autotune_plan
        return {"active": True,
                "source": p.get("source", "explicit"),
                "key": p.get("key", {}),
                "tolerance": p.get("tolerance"),
                "measured": p.get("measured"),
                "layers": {n: dict(v)
                           for n, v in self.layer_variants.items()}}

    # ------------------------------------------------------------------
    def init(self, key: Array) -> Params:
        """Initialize all learnable blobs (filler semantics)."""
        from .ops.fillers import fill
        from .ops.layers import stable_hash
        params: Params = {}
        for lname, specs in self.param_layout.items():
            lkey = jax.random.fold_in(key, stable_hash(lname))
            blobs = {}
            for i, (bname, shape, filler) in enumerate(specs):
                blobs[bname] = fill(jax.random.fold_in(lkey, i), filler,
                                    shape, self.dtype)
            params[lname] = blobs
        return params

    def input_names(self) -> List[str]:
        return [n for n, _, _ in self.input_specs]

    def make_dummy_inputs(self, batch_override: Optional[int] = None
                          ) -> Dict[str, Array]:
        out = {}
        for name, shape, kind in self.input_specs:
            if batch_override is not None:
                # time-major (":T") tops carry batch on axis 1, not 0
                ax = 1 if kind.endswith(":T") else 0
                shape = tuple(batch_override if i == ax else d
                              for i, d in enumerate(shape))
            out[name] = jnp.zeros(shape, self.dtype)
        return out

    # ------------------------------------------------------------------
    def apply(self, params: Params, inputs: Dict[str, Array], *,
              train: Optional[bool] = None, rng: Optional[Array] = None,
              net_state: Optional[Dict] = None,
              qscales: Optional[Dict] = None,
              layers: Optional[Sequence[str]] = None
              ) -> Tuple[Dict[str, Array], Dict]:
        """Forward pass. Returns (all blobs, updated_param_blobs).

        The second value maps layer name → [new blob arrays] for layers
        that update their own param blobs during the forward pass
        (BatchNorm running stats).  `Solver.train_step` merges it back
        into params with `merge_forward_state`; stat blobs are pinned to
        lr_mult = decay_mult = 0 so the optimizer never touches them.

        `qscales` ({layer: {blob: f32 scalar}}) carries the publish-
        time max-abs scales for quantized-resident serving weights
        (serving/quant.py): an op receiving an int8 param finds its
        dequant scale via Ctx.qscale and runs the dequant-free kernel
        path.  None (every training/eval caller) is inert.

        `layers` restricts the pass to a subset of compute_layers (run
        in net order) — the pipeline-stage body used by parallel/pp.py
        and serving/forward.py.  The caller supplies the stage's
        boundary blobs via `inputs` and must keep any layer named by
        `fused_bias_lrn` together with its producing conv (one stage),
        since the fused kernel pulls the conv's bias out of `params`."""
        if train is None:
            train = self.state.phase == Phase.TRAIN
        blobs: Dict[str, Array] = dict(inputs)
        ctx = L.Ctx(train=train, rng=rng,
                    state_in=net_state or {}, state_out={},
                    fused_relu_lrn=self.fused_relu_lrn,
                    defer_bias=self._defer_bias,
                    bias_lrn=self._bias_lrn_set,
                    qscales=qscales)
        cast = (self.compute_dtype != self.dtype)
        subset = None if layers is None else set(layers)
        compute = (self.compute_layers if subset is None else
                   [lp for lp in self.compute_layers
                    if lp.name in subset])
        for lp in compute:
            op = L.get_op(lp.type)
            ctx.layer_name = lp.name
            ctx.variant = self.layer_variants.get(lp.name)
            # per-layer compute dtype: the autotune plan's dtype variant
            # beats the net-wide compute_dtype (stat layers stay exempt
            # — see the f32_stats comment below); with no variant this
            # is exactly the pre-autotune cast, op for op
            vdt = (None if op.f32_stats
                   else self._variant_dtype.get(lp.name))
            target = (self.dtype if op.f32_stats
                      else (vdt or self.compute_dtype))
            # any per-layer dtype variant makes EVERY layer normalize
            # its floating bottoms to its own target (a bf16 layer's
            # output must cast back up entering its f32 consumer);
            # with no variants this reduces to the pre-autotune gate
            docast = cast or bool(self._variant_dtype)
            lparams = []
            if lp.name in self.param_layout:
                pd = params[lp.name]
                lparams = [pd[bname]
                           for bname, _, _ in self.param_layout[lp.name]]
            if lp.name in self.fused_bias_lrn:
                # bias-fused stem LRN: the producing conv's bias rides
                # in as params[0]; its gradient flows back to the conv
                # blob through the fused kernel's VJP
                lparams = [params[self.fused_bias_lrn[lp.name]]["bias"]] \
                    + lparams
            if docast and not op.f32_stats and lparams:
                # non-floating params (int8 quantized-resident serving
                # weights) must pass through untouched — a dtype-policy
                # cast would silently dequantize without the scale
                lparams = [p.astype(target)
                           if jnp.issubdtype(p.dtype, jnp.floating)
                           else p for p in lparams]
            bottoms = [blobs[b] for b in lp.bottom]
            if docast:
                # stat layers (BatchNorm) keep their INPUT at full
                # precision: E[x²]−E[x]² cancels catastrophically in
                # bf16 for unnormalized activations — their target is
                # self.dtype above
                bottoms = [b.astype(target)
                           if jnp.issubdtype(b.dtype, jnp.floating)
                           and b.dtype != target else b
                           for b in bottoms]
            if self.remat and train and lparams \
                    and not op.f32_stats:
                # only parameterized layers are checkpointed — wrapping
                # elementwise ops would just block XLA fusion; BatchNorm
                # is excluded because its running-stat side channel
                # (ctx.state_out) must not cross the remat boundary
                kw = ({"policy": self.remat_policy}
                      if self.remat_policy is not None else {})
                fn = jax.checkpoint(
                    lambda p, b, op=op, lp=lp, ctx=ctx:
                    op.apply(ctx, lp, p, b), **kw)
                tops = fn(lparams, bottoms)
            else:
                tops = op.apply(ctx, lp, lparams, bottoms)
            for name, val in zip(lp.top, tops):
                blobs[name] = val
        return blobs, ctx.state_out

    def loss(self, params: Params, inputs: Dict[str, Array], *,
             train: bool = True, rng: Optional[Array] = None,
             net_state: Optional[Dict] = None
             ) -> Tuple[Array, Tuple[Dict[str, Array], Dict]]:
        """Total weighted loss (for jax.value_and_grad(has_aux=True))."""
        blobs, new_state = self.apply(params, inputs, train=train, rng=rng,
                                      net_state=net_state)
        # the scalar loss ACCUMULATES in f32 regardless of compute dtype
        # (a bf16 running sum over a large blob drops addends)
        total = jnp.zeros((), jnp.float32)
        for name, w in self.loss_weights.items():
            total = total + w * jnp.sum(blobs[name],
                                        dtype=jnp.float32)
        return total, (blobs, new_state)

    def merge_forward_state(self, params: Params,
                            forward_state: Dict[str, List[Array]]) -> Params:
        """Overwrite self-updating param blobs (BatchNorm stats) with the
        values produced by the last forward pass."""
        if not forward_state:
            return params
        out = {ln: dict(bl) for ln, bl in params.items()}
        for lname, blobs in forward_state.items():
            if lname not in self.param_layout:
                continue   # side-channel keys (LSTM hidden, HDF5Output)
            for (bname, _, _), arr in zip(self.param_layout[lname], blobs):
                out[lname][bname] = arr
        return out

    def stat_param_layers(self) -> List[str]:
        """Layers whose param blobs are running statistics, not weights
        (op-level f32_stats flag, e.g. BatchNorm)."""
        return [lp.name for lp in self.compute_layers
                if L.get_op(lp.type).f32_stats]

    def num_params(self, params: Optional[Params] = None) -> int:
        if params is not None:
            return sum(int(x.size) for lb in params.values()
                       for x in lb.values())
        return sum(math.prod(s) for specs in self.param_layout.values()
                   for (_, s, _) in specs)
