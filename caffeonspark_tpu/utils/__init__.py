"""Utilities: tracing/profiling, metrics."""

from .tracing import StepTimer, profile_trace
