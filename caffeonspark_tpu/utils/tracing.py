"""Tracing / profiling: the idiomatic superset of the reference's
observability (SURVEY §5.1 — glog iteration display + manual PerfTest/
Simulator drivers; no structured tracing).

  * StepTimer — per-step wall-clock with EMA smoothing, records/sec, and
    the solver `display` cadence (Caffe's "Iteration N, loss = ..." log)
  * profile_trace — context manager around jax.profiler.trace; produces
    a TensorBoard-loadable trace directory of XLA device timelines
    (enable in mini_cluster with -profile DIR)
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional


class StepTimer:
    def __init__(self, *, batch_size: int = 0, ema: float = 0.05):
        self.batch_size = batch_size
        self.ema = ema
        self._t0: Optional[float] = None
        self._last: Optional[float] = None
        self.step_time: Optional[float] = None   # EMA seconds/step
        self.steps = 0

    def start(self) -> None:
        self._t0 = self._last = time.perf_counter()

    def tick(self, n: int = 1) -> float:
        """Call once per completed dispatch covering `n` solver steps
        (n > 1 for a fused K-step chunk: the elapsed time is averaged
        over the chunk so it/s stays per-STEP); returns the elapsed
        seconds for the whole dispatch."""
        now = time.perf_counter()
        if self._last is None:
            self.start()
            self._last = now
            return 0.0
        dt = now - self._last
        self._last = now
        n = max(1, n)
        self.steps += n
        per = dt / n
        self.step_time = per if self.step_time is None else (
            (1 - self.ema) * self.step_time + self.ema * per)
        return dt

    @property
    def steps_per_sec(self) -> float:
        return 1.0 / self.step_time if self.step_time else 0.0

    @property
    def records_per_sec(self) -> float:
        return self.batch_size * self.steps_per_sec

    def summary(self) -> str:
        """Totals use wall-clock averages (steps/total), not the EMA —
        the EMA reflects only recent steps and would disagree with the
        printed total time after a long first-compile step."""
        total = (time.perf_counter() - self._t0) if self._t0 else 0.0
        avg = self.steps / total if total > 0 else 0.0
        return (f"{self.steps} steps in {total:.1f}s "
                f"({avg:.1f} it/s"
                + (f", {self.batch_size * avg:.0f} rec/s"
                   if self.batch_size else "") + ")")


@contextlib.contextmanager
def profile_trace(log_dir: Optional[str]) -> Iterator[None]:
    """jax.profiler trace when log_dir is set; no-op otherwise."""
    if not log_dir:
        yield
        return
    import jax
    with jax.profiler.trace(log_dir):
        yield
