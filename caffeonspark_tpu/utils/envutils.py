"""Env-knob parsing, shared by every layer that reads numeric knobs.

One definition instead of the per-module copies that had accumulated
(serving/batcher.py grew the first shared one in PR 8; the sync-mode /
chaos layers would have been the 3rd and 4th).  Unset or empty always
means the default; a non-numeric value is a config error — `strict`
(the trainer-side default) raises a ValueError naming the knob, while
`strict=False` (the serving-side behavior, where a bad knob must not
take a running fleet down) logs and falls back to the default.
"""

from __future__ import annotations

import logging
import os

_LOG = logging.getLogger(__name__)


def env_num(name: str, default: float, *, strict: bool = True
            ) -> float:
    v = os.environ.get(name, "")
    if not v:
        return default
    try:
        return float(v)
    except ValueError:
        if strict:
            raise ValueError(
                f"{name}={v!r}: expected a number") from None
        _LOG.warning("ignoring non-numeric %s=%r", name, v)
        return default


def env_int(name: str, default: int, *, strict: bool = True) -> int:
    v = os.environ.get(name, "")
    if not v:
        return default
    try:
        return int(v)
    except ValueError:
        if strict:
            raise ValueError(
                f"{name}={v!r}: expected an integer") from None
        _LOG.warning("ignoring non-integer %s=%r", name, v)
        return default
