"""Analytic FLOP estimates for a constructed Net.

Counts the multiply-accumulate work of the parametrised layers
(Convolution / Deconvolution / InnerProduct / LSTM-style weights) from
the weight blob shapes and inferred top shapes — the >99% of CaffeNet's
arithmetic that lands on the MXU.  Elementwise layers (ReLU, LRN,
Pooling, Softmax) are ignored; they are HBM-bound, not FLOP-bound.

Used by bench.py for MFU: images/sec alone can't be sanity-checked
against chip peak without a FLOP count (reference analog: the
throughput harnesses in `caffe-distri/.../PerfTest.java:69-118` report
rates only — no roofline; this is the TPU-native upgrade).
"""

from __future__ import annotations

from math import prod


def forward_flops(net) -> int:
    """Estimated forward-pass FLOPs for one batch through `net`.

    2 * (output elements) * (MACs per output element), where MACs per
    output element = prod(weight.shape[1:]) for every weighted layer:
      Convolution  weight (K, C/g, kh, kw), top (N, K, Ho, Wo)
      InnerProduct weight (K, I),           top (N, K)
      LSTM/RNN     weight (4H, I) etc.      top (T, N, H)
    Deconvolution scatters from the bottom instead: weight
    (C, K/g, kh, kw) applied per bottom element.
    """
    return sum(layer_forward_flops(net).values())


def layer_forward_flops(net) -> dict:
    """{layer name: forward FLOPs} — the one copy of the per-layer
    accounting (scripts/roofline.py consumes this too)."""
    out: dict = {}
    for lp in net.compute_layers:
        specs = net.param_layout.get(lp.name)
        if not specs:
            continue
        tops = net._top_shapes[lp.name]
        if not tops:
            continue
        first_top = next(iter(tops.values()))
        total = 0
        if lp.type == "Embed":
            out[lp.name] = 0     # gather, not a matmul: ~0 FLOPs
            continue
        if lp.type == "MultiHeadAttention":
            # projections apply the FULL weight per (t, b) position
            # (top is (T, B, D), not (T, B, 3D)), plus the two
            # attention einsums (QK^T and PV: 2 * 2*B*H*T^2*hd)
            t_s, b_s = first_top[0], first_top[1]
            for (pname, pshape, _) in specs:
                total += 2 * t_s * b_s * prod(pshape)
            ap = lp.attention_param
            total += 4 * b_s * int(ap.num_heads) * t_s * t_s \
                * int(ap.head_dim)
            out[lp.name] = total
            continue
        for (pname, pshape, _) in specs:
            if len(pshape) < 2 or "bias" in pname:
                continue
            if lp.type == "Deconvolution":
                # one MAC per bottom element per kernel tap
                n, c = first_top[0], pshape[0]
                # bottom spatial size = prod(top)/N/K * ... — recover
                # from blob_shapes via the bottom name when available
                bshape = net.blob_shapes.get(lp.bottom[0])
                ref = prod(bshape) if bshape else prod(first_top)
                total += 2 * ref * prod(pshape[1:])
            elif lp.type in ("LSTM", "RNN"):
                # gate weights (4H, I)/(4H, H) apply FULLY per
                # (t, b) step — the top (T, B, H) only exposes H, so
                # the generic rule would undercount 4x
                total += 2 * prod(first_top[:2]) * prod(pshape)
            else:
                total += 2 * prod(first_top) * prod(pshape[1:])
        out[lp.name] = total
    return out


def train_step_flops(net) -> int:
    """Forward + backward + update ≈ 3x forward (dL/dW and dL/dx are
    each another pass of the same matmuls; the elementwise optimizer
    update is negligible)."""
    return 3 * forward_flops(net)
