"""Analytic FLOP estimates for a constructed Net.

Counts the multiply-accumulate work of the parametrised layers
(Convolution / Deconvolution / InnerProduct / LSTM-style weights) from
the weight blob shapes and inferred top shapes — the >99% of CaffeNet's
arithmetic that lands on the MXU.  Elementwise layers (ReLU, LRN,
Pooling, Softmax) are ignored; they are HBM-bound, not FLOP-bound.

Used by bench.py for MFU: images/sec alone can't be sanity-checked
against chip peak without a FLOP count (reference analog: the
throughput harnesses in `caffe-distri/.../PerfTest.java:69-118` report
rates only — no roofline; this is the TPU-native upgrade).
"""

from __future__ import annotations

from math import prod


def forward_flops(net) -> int:
    """Estimated forward-pass FLOPs for one batch through `net`.

    2 * (output elements) * (MACs per output element), where MACs per
    output element = prod(weight.shape[1:]) for every weighted layer:
      Convolution  weight (K, C/g, kh, kw), top (N, K, Ho, Wo)
      InnerProduct weight (K, I),           top (N, K)
      LSTM/RNN     weight (4H, I) etc.      top (T, N, H)
    Deconvolution scatters from the bottom instead: weight
    (C, K/g, kh, kw) applied per bottom element.
    """
    total = 0
    for lp in net.compute_layers:
        specs = net.param_layout.get(lp.name)
        if not specs:
            continue
        tops = net._top_shapes[lp.name]
        if not tops:
            continue
        first_top = next(iter(tops.values()))
        for (pname, pshape, _) in specs:
            if len(pshape) < 2 or "bias" in pname:
                continue
            if lp.type == "Deconvolution":
                # one MAC per bottom element per kernel tap
                n, c = first_top[0], pshape[0]
                # bottom spatial size = prod(top)/N/K * ... — recover
                # from blob_shapes via the bottom name when available
                bshape = net.blob_shapes.get(lp.bottom[0])
                ref = prod(bshape) if bshape else prod(first_top)
            else:
                ref = prod(first_top)
            total += 2 * ref * prod(pshape[1:])
    return total


def train_step_flops(net) -> int:
    """Forward + backward + update ≈ 3x forward (dL/dW and dL/dx are
    each another pass of the same matmuls; the elementwise optimizer
    update is negligible)."""
    return 3 * forward_flops(net)
