"""Remote-filesystem plumbing: the FSUtils.scala analog.

The reference writes snapshots/outputs locally and copies them to HDFS
when the configured path isn't local (`FSUtils.scala:21-89`
CopyFileToHDFS / GenModelOutputPath).  Here any fsspec-supported scheme
works the same way — `hdfs://`, `gs://`, `s3://`, `memory://` (tests)
— while plain paths and `file:` URIs stay on the fast local-open path
with zero fsspec involvement.
"""

from __future__ import annotations

import os
import posixpath

LOCAL_PREFIXES = ("file://", "file:")


def strip_local(path: str) -> str:
    for p in LOCAL_PREFIXES:
        if path.startswith(p):
            return path[len(p):] or "/"
    return path


def is_remote(path: str) -> bool:
    if "://" not in path:
        return False
    return not path.startswith("file://")


def _fs(path: str):
    import fsspec
    fs, p = fsspec.core.url_to_fs(path)
    return fs, p


def join(base: str, *parts: str) -> str:
    if is_remote(base):
        return posixpath.join(base, *parts)
    return os.path.join(strip_local(base), *parts)


def dirname(path: str) -> str:
    if is_remote(path):
        return posixpath.dirname(path)
    return os.path.dirname(os.path.abspath(strip_local(path)))


def basename(path: str) -> str:
    return posixpath.basename(path) if is_remote(path) \
        else os.path.basename(path)


def exists(path: str) -> bool:
    if is_remote(path):
        fs, p = _fs(path)
        return fs.exists(p)
    return os.path.exists(strip_local(path))


def makedirs(path: str) -> None:
    if is_remote(path):
        fs, p = _fs(path)
        fs.makedirs(p, exist_ok=True)
    elif path:
        os.makedirs(strip_local(path), exist_ok=True)


def listdir(path: str) -> list:
    """Entry basenames in a directory; [] when the directory is missing.
    Works on any fsspec scheme — the supervisor's snapshot discovery and
    stall detection go through here so `-output gs://bucket/run` behaves
    like a local dir (FSUtils.scala:21-89 analog surface)."""
    if is_remote(path):
        fs, p = _fs(path)
        # fsspec caches both filesystem instances and their dircache —
        # a supervisor polling for new snapshots written by OTHER
        # processes would otherwise see a frozen listing forever
        try:
            fs.invalidate_cache(p)
        except Exception:  # noqa: BLE001 — backend-specific, optional
            pass
        if not fs.exists(p):
            return []
        return [posixpath.basename(e.rstrip("/"))
                for e in fs.ls(p, detail=False)]
    p = strip_local(path)
    return os.listdir(p) if os.path.isdir(p) else []


def getmtime(path: str) -> float:
    """Modification time, best-effort on remote schemes (object stores
    report LastModified/mtime under different keys; 0.0 when the backend
    exposes none — callers needing a monotonic progress signal should
    prefer content-derived stamps, see tools/supervisor.py)."""
    if not is_remote(path):
        return os.path.getmtime(strip_local(path))
    fs, p = _fs(path)
    info = fs.info(p)
    for key in ("mtime", "LastModified", "last_modified", "created"):
        v = info.get(key)
        if v is None:
            continue
        if hasattr(v, "timestamp"):
            return v.timestamp()
        try:
            return float(v)
        except (TypeError, ValueError):
            continue
    return 0.0


def open_file(path: str, mode: str = "rb"):
    if is_remote(path):
        import fsspec
        return fsspec.open(path, mode).open()
    p = strip_local(path)
    if any(m in mode for m in "wa"):
        d = os.path.dirname(os.path.abspath(p))
        os.makedirs(d, exist_ok=True)
    return open(p, mode)


def write_bytes(path: str, data: bytes) -> None:
    if is_remote(path):
        with open_file(path, "wb") as f:
            f.write(data)
        return
    # local: temp + rename so a crash mid-write can never leave a
    # truncated file where a resumable snapshot is expected
    def _write(tmp):
        with open(tmp, "wb") as f:
            f.write(data)
    atomic_write_local(strip_local(path), _write)


def atomic_write_local(path: str, write_fn) -> None:
    """Run write_fn(tmp_path) then os.replace into place — readers (and
    the elastic-recovery supervisor, and the deploy canary loading a
    just-written candidate snapshot) only ever see complete files.

    Crash posture: a writer killed mid-write leaves only the orphaned
    `.tmp.<pid>` file — the target keeps its previous complete content
    (every snapshot-discovery pattern excludes `.tmp.`).  The tmp is
    fsynced BEFORE the rename so a host crash cannot reorder the
    rename ahead of the data and expose a zero-length "complete" file;
    the directory entry is fsynced after, so the rename itself is
    durable (tests/test_checkpoint.py kill-mid-save drill)."""
    path = os.path.abspath(path)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        write_fn(tmp)
        fd = os.open(tmp, os.O_RDWR)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, path)
        try:
            dfd = os.open(os.path.dirname(path), os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass     # platforms without directory fsync
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def read_bytes(path: str) -> bytes:
    with open_file(path, "rb") as f:
        return f.read()


def upload(local_path: str, dest: str) -> None:
    """CopyFileToHDFS analog: local file -> remote path (overwrite)."""
    fs, p = _fs(dest)
    parent = posixpath.dirname(p)
    if parent:
        fs.makedirs(parent, exist_ok=True)
    fs.put_file(local_path, p)


def download(src: str, local_path: str) -> str:
    """Remote file -> local path; returns local_path."""
    os.makedirs(os.path.dirname(os.path.abspath(local_path)),
                exist_ok=True)
    fs, p = _fs(src)
    fs.get_file(p, local_path)
    return local_path
