"""Remote-filesystem plumbing: the FSUtils.scala analog.

The reference writes snapshots/outputs locally and copies them to HDFS
when the configured path isn't local (`FSUtils.scala:21-89`
CopyFileToHDFS / GenModelOutputPath).  Here any fsspec-supported scheme
works the same way — `hdfs://`, `gs://`, `s3://`, `memory://` (tests)
— while plain paths and `file:` URIs stay on the fast local-open path
with zero fsspec involvement.
"""

from __future__ import annotations

import os
import posixpath

LOCAL_PREFIXES = ("file://", "file:")


def strip_local(path: str) -> str:
    for p in LOCAL_PREFIXES:
        if path.startswith(p):
            return path[len(p):] or "/"
    return path


def is_remote(path: str) -> bool:
    if "://" not in path:
        return False
    return not path.startswith("file://")


def _fs(path: str):
    import fsspec
    fs, p = fsspec.core.url_to_fs(path)
    return fs, p


def join(base: str, *parts: str) -> str:
    if is_remote(base):
        return posixpath.join(base, *parts)
    return os.path.join(strip_local(base), *parts)


def dirname(path: str) -> str:
    if is_remote(path):
        return posixpath.dirname(path)
    return os.path.dirname(os.path.abspath(strip_local(path)))


def basename(path: str) -> str:
    return posixpath.basename(path) if is_remote(path) \
        else os.path.basename(path)


def exists(path: str) -> bool:
    if is_remote(path):
        fs, p = _fs(path)
        return fs.exists(p)
    return os.path.exists(strip_local(path))


def makedirs(path: str) -> None:
    if is_remote(path):
        fs, p = _fs(path)
        fs.makedirs(p, exist_ok=True)
    elif path:
        os.makedirs(strip_local(path), exist_ok=True)


def open_file(path: str, mode: str = "rb"):
    if is_remote(path):
        import fsspec
        return fsspec.open(path, mode).open()
    p = strip_local(path)
    if any(m in mode for m in "wa"):
        d = os.path.dirname(os.path.abspath(p))
        os.makedirs(d, exist_ok=True)
    return open(p, mode)


def write_bytes(path: str, data: bytes) -> None:
    if is_remote(path):
        with open_file(path, "wb") as f:
            f.write(data)
        return
    # local: temp + rename so a crash mid-write can never leave a
    # truncated file where a resumable snapshot is expected
    def _write(tmp):
        with open(tmp, "wb") as f:
            f.write(data)
    atomic_write_local(strip_local(path), _write)


def atomic_write_local(path: str, write_fn) -> None:
    """Run write_fn(tmp_path) then os.replace into place — readers (and
    the elastic-recovery supervisor) only ever see complete files."""
    path = os.path.abspath(path)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        write_fn(tmp)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def read_bytes(path: str) -> bytes:
    with open_file(path, "rb") as f:
        return f.read()


def upload(local_path: str, dest: str) -> None:
    """CopyFileToHDFS analog: local file -> remote path (overwrite)."""
    fs, p = _fs(dest)
    parent = posixpath.dirname(p)
    if parent:
        fs.makedirs(parent, exist_ok=True)
    fs.put_file(local_path, p)


def download(src: str, local_path: str) -> str:
    """Remote file -> local path; returns local_path."""
    os.makedirs(os.path.dirname(os.path.abspath(local_path)),
                exist_ok=True)
    fs, p = _fs(src)
    fs.get_file(p, local_path)
    return local_path
