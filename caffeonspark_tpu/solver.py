"""Solver: Caffe SolverParameter semantics as a jitted JAX train step.

TPU-native equivalent of caffe::Solver/SGDSolver consumed through
`CaffeNet<Dtype>::train` (`caffe-distri/src/main/cpp/CaffeNet.cpp:707-729`,
`solver->Step(1)`), re-designed as a pure function:

    (params, opt_state, inputs, rng) --train_step--> (params', opt_state',
                                                      outputs)

with `jax.jit(..., donate_argnums=(0, 1))` so parameter and momentum
buffers update in place in HBM.  Reproduced Caffe behaviors:

  * learning-rate policies fixed/step/exp/inv/multistep/poly/sigmoid
    (sgd_solver.cpp GetLearningRate), computed with jnp ops so the
    iteration counter stays a traced scalar — no recompiles per step;
  * per-blob lr_mult/decay_mult from layer `param {}` specs;
  * L2/L1 regularization (weight_decay × decay_mult);
  * clip_gradients by global L2 norm;
  * iter_size gradient accumulation;
  * solver types SGD / Nesterov / AdaGrad / RMSProp / AdaDelta / Adam
    (update rules follow the corresponding caffe solver .cpp files);
  * rank/device seeding: seed = random_seed + rank
    (`CaffeNet.cpp:614-618`).

Gradient averaging across devices (the 1/solver_count scaling in
`parallel_cpu.cpp:120-122` + SocketSync shard exchange) is NOT here — it
is a `jax.lax.pmean` inserted by `parallel.dp` when the step is wrapped
for a mesh.
"""

from __future__ import annotations

import os
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .net import Net, Params
from .proto.caffe import (NetParameter, NetState, Phase, SolverParameter)

Array = jax.Array


class OptState(NamedTuple):
    """Optimizer state: iteration counter + per-param history pytrees."""
    iter: Array                 # int32 scalar
    history: Params             # momentum / accumulated squared grads
    history2: Params            # second moment (Adam) / delta accum (AdaDelta)


def _zeros_like_params(params: Params, dtype=None) -> Params:
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros_like(p, dtype=dtype), params)


def learning_rate(sp: SolverParameter, it: Array) -> Array:
    """Caffe GetLearningRate — traced-friendly."""
    policy = sp.lr_policy or "fixed"
    base = sp.base_lr
    itf = it.astype(jnp.float32)
    if policy == "fixed":
        return jnp.asarray(base, jnp.float32)
    if policy == "step":
        step = jnp.floor(itf / max(1, sp.stepsize))
        return base * jnp.power(sp.gamma, step)
    if policy == "exp":
        return base * jnp.power(sp.gamma, itf)
    if policy == "inv":
        return base * jnp.power(1.0 + sp.gamma * itf, -sp.power)
    if policy == "multistep":
        steps = jnp.asarray(list(sp.stepvalue) or [1 << 30], jnp.int32)
        current = jnp.sum((it >= steps).astype(jnp.int32))
        return base * jnp.power(sp.gamma, current.astype(jnp.float32))
    if policy == "poly":
        frac = jnp.clip(itf / max(1, sp.max_iter), 0.0, 1.0)
        return base * jnp.power(1.0 - frac, sp.power)
    if policy == "sigmoid":
        return base / (1.0 + jnp.exp(-sp.gamma * (itf - sp.stepsize)))
    raise ValueError(f"unknown lr_policy {policy!r}")


class Solver:
    """Owns the train/test Nets compiled from a SolverParameter and builds
    the jitted train/eval steps."""

    def __init__(self, solver_param: SolverParameter,
                 net_param: Optional[NetParameter] = None, *,
                 rank: int = 0, dtype=jnp.float32, compute_dtype=None,
                 state_dtype=None, grad_sync=None):
        self.param = solver_param
        self.rank = rank
        # optimizer-history dtype (default: match each param blob).
        # bfloat16 halves the optimizer's HBM round trip — on CaffeNet
        # b256 that is ~300 MB/step, the single biggest remaining lever
        # per scripts/roofline.py (fc6/fc7 are optimizer-traffic-bound,
        # not matmul-bound).  _apply_update already preserves history
        # dtype (h_n.astype(h.dtype)): arithmetic upcasts to f32, only
        # the STORED momentum is rounded.  COS_STATE_DTYPE=bfloat16
        # flips it globally.
        if state_dtype is None:
            env = os.environ.get("COS_STATE_DTYPE", "")
            state_dtype = jnp.dtype(env).type if env else None
        stype = (solver_param.type or "SGD").upper()
        if (state_dtype is not None
                and jnp.dtype(state_dtype).itemsize < 4
                and stype not in ("SGD", "NESTEROV")):
            # second-moment accumulators (Adam/AdaGrad/RMSProp/AdaDelta
            # keep them in `history`/`history2`) change by ~1e-3
            # relative per step — below bf16 ulp, so a reduced state
            # dtype would freeze them after warm-up.  Only the
            # momentum-style first moments tolerate it.
            import logging
            logging.getLogger(__name__).warning(
                "COS_STATE_DTYPE=%s ignored for solver type %s "
                "(second-moment accumulators need >=f32)",
                jnp.dtype(state_dtype).name, stype)
            state_dtype = None
        self.state_dtype = state_dtype
        if net_param is None:
            raise ValueError("net_param required (driver resolves "
                             "solver.net path → NetParameter)")
        self.net_param = net_param

        train_state = NetState(phase=Phase.TRAIN)
        if solver_param.has("train_state"):
            train_state = solver_param.train_state.clone()
            train_state.phase = Phase.TRAIN
        self.train_net = Net(net_param, train_state, dtype=dtype,
                             compute_dtype=compute_dtype)

        test_state = NetState(phase=Phase.TEST)
        if solver_param.test_state:
            test_state = solver_param.test_state[0].clone()
            test_state.phase = Phase.TEST
        try:
            self.test_net: Optional[Net] = Net(net_param, test_state,
                                               dtype=dtype,
                                               compute_dtype=compute_dtype)
            if not self.test_net.compute_layers:
                self.test_net = None
        except Exception:
            self.test_net = None

        seed = solver_param.random_seed
        if seed < 0:
            seed = 1701  # caffe uses a clock seed; fixed default for replay
        # weight init must be IDENTICAL on every rank (the reference
        # syncs weights at start via the on_start exchange; with SPMD
        # replication, identical init IS the sync) — only the
        # per-iteration dropout/augment stream is rank-decorrelated
        # (seed = random_seed + rank, CaffeNet.cpp:614-618)
        self.init_key = jax.random.key(int(seed))
        self.key = jax.random.key(int(seed) + rank)
        self.solver_type = (solver_param.type or "SGD").upper()

        self._lr_mults, self._decay_mults = self._collect_mults()
        # explicit gradient-exchange layer (COS_GRAD_SYNC): inert in
        # `default` mode; ParallelSolver binds the mesh before any step
        # is traced.  Runtime import — parallel.dp imports this module.
        if grad_sync is None:
            from .parallel.gradsync import make_gradsync
            grad_sync = make_gradsync(self.train_net)
        self.grad_sync = grad_sync
        # sync-mode policy (COS_SYNC_MODE): resolved HERE, once, like
        # grad_sync — lockstep (the default) constructs nothing and
        # changes nothing; the relaxed modes are driven by the runtime
        # (mini_cluster) through parallel/syncmode.py, the traced step
        # itself is identical in every mode
        from .parallel.syncmode import resolve_policy
        self.sync_policy = resolve_policy()
        # COS_RECOMPILE_GUARD=1: every jitted step is watched and a
        # steady-state recompile (shape drift, trace-time host read)
        # raises instead of silently storming XLA (analysis/runtime.py)
        from .analysis.runtime import maybe_recompile_guard
        self._recompile_guard = maybe_recompile_guard("solver")
        self._jit_train_step = None
        self._jit_train_step_many: Dict[int, object] = {}
        self._jit_eval_step = None

    # ------------------------------------------------------------------
    def _collect_mults(self) -> Tuple[Params, Params]:
        """Per-blob lr/decay multipliers from layer `param {}` specs."""
        lr_m: Dict[str, Dict[str, float]] = {}
        dc_m: Dict[str, Dict[str, float]] = {}
        net = self.train_net
        by_name = {lp.name: lp for lp in net.compute_layers}
        for lname, specs in net.param_layout.items():
            lp = by_name[lname]
            lr_m[lname] = {}
            dc_m[lname] = {}
            for i, (bname, _, _) in enumerate(specs):
                if i < len(lp.param):
                    ps = lp.param[i]
                    lr_m[lname][bname] = (ps.lr_mult
                                          if ps.has("lr_mult") else 1.0)
                    dc_m[lname][bname] = (ps.decay_mult
                                          if ps.has("decay_mult") else 1.0)
                else:
                    lr_m[lname][bname] = 1.0
                    dc_m[lname][bname] = 1.0
        # BatchNorm stat blobs are updated by the forward pass, never by
        # the optimizer (Caffe forces lr_mult 0 on them)
        for lname in net.stat_param_layers():
            for bname in lr_m.get(lname, {}):
                lr_m[lname][bname] = 0.0
                dc_m[lname][bname] = 0.0
        return lr_m, dc_m

    # ------------------------------------------------------------------
    def init(self) -> Tuple[Params, OptState]:
        params = self.train_net.init(self.init_key)
        return params, self.init_state(params)

    def init_state(self, params: Params) -> OptState:
        return OptState(
            iter=jnp.zeros((), jnp.int32),
            history=_zeros_like_params(params, self.state_dtype),
            history2=_zeros_like_params(params, self.state_dtype))

    # ------------------------------------------------------------------
    def _apply_update(self, params: Params, grads: Params, state: OptState,
                      lr: Array) -> Tuple[Params, OptState]:
        sp = self.param
        momentum = sp.momentum
        wd = sp.weight_decay
        l1 = sp.regularization_type == "L1"
        t = self.solver_type
        it1 = (state.iter + 1).astype(jnp.float32)

        # Caffe order (SGDSolver::ApplyUpdate): ClipGradients on the raw
        # accumulated diffs FIRST, then Normalize (1/iter_size), then
        # Regularize.  Our grads arrive already normalized (sum/iter_size),
        # and ||sum|| = iter_size*||mean||, so clipping the mean against
        # threshold/iter_size is exactly Caffe's clip-the-sum
        if sp.clip_gradients > 0:
            thresh = sp.clip_gradients / max(1, int(sp.iter_size))
            leaves = jax.tree_util.tree_leaves(grads)
            gnorm = jnp.sqrt(sum(jnp.sum(g * g) for g in leaves))
            scale = jnp.where(gnorm > thresh, thresh / gnorm, 1.0)
            grads = jax.tree_util.tree_map(lambda g: g * scale, grads)

        def reg(g, w, dm):
            if wd == 0.0 or dm == 0.0:
                return g
            if l1:
                return g + wd * dm * jnp.sign(w)
            return g + wd * dm * w

        grads = {ln: {bn: reg(g, params[ln][bn],
                              self._decay_mults[ln][bn])
                      for bn, g in bl.items()}
                 for ln, bl in grads.items()}

        new_p: Params = {}
        new_h: Params = {}
        new_h2: Params = {}
        for ln, bl in params.items():
            new_p[ln] = {}
            new_h[ln] = {}
            new_h2[ln] = {}
            for bn, w in bl.items():
                g = grads[ln][bn]
                h = state.history[ln][bn]
                h2 = state.history2[ln][bn]
                local_lr = lr * self._lr_mults[ln][bn]
                if t == "SGD":
                    upd = local_lr * g + momentum * h
                    w2, h_n, h2_n = w - upd, upd, h2
                elif t == "NESTEROV":
                    h_n = local_lr * g + momentum * h
                    upd = (1 + momentum) * h_n - momentum * h
                    w2, h2_n = w - upd, h2
                elif t == "ADAGRAD":
                    h_n = h + g * g
                    w2 = w - local_lr * g / (jnp.sqrt(h_n) + sp.delta)
                    h2_n = h2
                elif t == "RMSPROP":
                    h_n = sp.rms_decay * h + (1 - sp.rms_decay) * g * g
                    w2 = w - local_lr * g / (jnp.sqrt(h_n) + sp.delta)
                    h2_n = h2
                elif t == "ADADELTA":
                    h_n = momentum * h + (1 - momentum) * g * g
                    upd = g * jnp.sqrt((h2 + sp.delta) / (h_n + sp.delta))
                    h2_n = momentum * h2 + (1 - momentum) * upd * upd
                    w2 = w - local_lr * upd
                elif t == "ADAM":
                    b1, b2 = momentum, sp.momentum2
                    h_n = b1 * h + (1 - b1) * g
                    h2_n = b2 * h2 + (1 - b2) * g * g
                    corr = (jnp.sqrt(1.0 - jnp.power(b2, it1))
                            / (1.0 - jnp.power(b1, it1)))
                    w2 = w - local_lr * corr * h_n / (jnp.sqrt(h2_n)
                                                      + sp.delta)
                else:
                    raise ValueError(f"unknown solver type {t!r}")
                # keep each blob's dtype (the f32 lr scalar would
                # silently upcast bf16 nets to f32 after one update)
                new_p[ln][bn] = w2.astype(w.dtype)
                new_h[ln][bn] = h_n.astype(h.dtype)
                new_h2[ln][bn] = h2_n.astype(h2.dtype)
        return new_p, OptState(iter=state.iter + 1, history=new_h,
                               history2=new_h2)

    # ------------------------------------------------------------------
    def train_step_fn(self):
        """The pure (params, opt_state, inputs, rng) step — wrap with jit
        or hand to parallel.dp for mesh execution.

        With `iter_size > 1` (gradient accumulation, solver prototxt),
        the incoming batch is reshaped to (iter_size, B/iter_size, ...)
        INSIDE the step (so every caller's (B, ...) contract still
        holds) and a `lax.scan` accumulates gradients over the
        sub-batches before ONE optimizer update — Caffe's
        Normalize-by-iter_size semantics.  BatchNorm running stats are
        threaded through the scan carry so each forward compounds them
        (Caffe updates per forward); reported output blobs are the mean
        over sub-batches."""
        net = self.train_net
        iter_size = max(1, int(self.param.iter_size))
        tmajor = {n for n, _, kind in net.input_specs
                  if kind.endswith(":T")}
        stat_layers = net.stat_param_layers()
        # explicit gradient exchange (parallel/gradsync.py): backward
        # hooks emit each bucket's collective mid-backward when
        # eligible; otherwise the finished grad pytree is transformed
        # below.  Both trace-time booleans — `default` mode adds no ops
        # and the step stays byte-identical to the implicit exchange.
        gs = self.grad_sync
        hooks_on = gs is not None and gs.use_hooks(iter_size)
        exchange_on = (gs is not None and gs.enabled and not hooks_on)

        def loss_and_grads(params, inputs, rng):
            def loss_fn(p):
                if hooks_on:
                    p = gs.attach(p)
                total, (blobs, fwd_state) = net.loss(p, inputs,
                                                     train=True, rng=rng)
                return total, (blobs, fwd_state)
            return jax.value_and_grad(loss_fn, has_aux=True)(params)

        def _split(inputs):
            out = {}
            for k, v in inputs.items():
                ax = 1 if k in tmajor else 0
                b = v.shape[ax]
                if b % iter_size:
                    raise ValueError(
                        f"batch {b} not divisible by iter_size "
                        f"{iter_size} (input {k!r})")
                if ax == 0:
                    out[k] = v.reshape((iter_size, b // iter_size)
                                       + v.shape[1:])
                else:
                    t = v.shape[0]
                    r = v.reshape((t, iter_size, b // iter_size)
                                  + v.shape[2:])
                    out[k] = jnp.moveaxis(r, 1, 0)
            return out

        def step(params: Params, state: OptState,
                 inputs: Dict[str, Array], rng: Array):
            if iter_size == 1:
                (loss, (blobs, fwd_state)), grads = loss_and_grads(
                    params, inputs, rng)
                if exchange_on:
                    grads = gs.exchange(grads, rng)
                outputs = {name: blobs[name]
                           for name in net.output_blobs}
            else:
                subs = _split(inputs)

                def body(carry, xs):
                    stats, gacc, oacc = carry
                    sub, sub_rng = xs
                    p = {**params, **stats}
                    (l, (blobs, fwd)), g = loss_and_grads(p, sub,
                                                          sub_rng)
                    gacc = jax.tree_util.tree_map(jnp.add, gacc, g)
                    oacc = {name: oacc[name] + blobs[name]
                            for name in oacc}
                    merged = net.merge_forward_state(
                        {ln: stats[ln] for ln in stats}, fwd)
                    return (merged, gacc, oacc), None

                zero_g = jax.tree_util.tree_map(jnp.zeros_like, params)
                # output shapes for the RUNTIME sub-batch (construction
                # shapes in net.blob_shapes carry the config batch size)
                sub0 = jax.tree_util.tree_map(lambda v: v[0], subs)
                out_abs = jax.eval_shape(
                    lambda p, s: {n: net.apply(p, s, train=True,
                                               rng=rng)[0][n]
                                  for n in net.output_blobs},
                    params, sub0)
                zero_o = {n: jnp.zeros(a.shape, a.dtype)
                          for n, a in out_abs.items()}
                stats0 = {ln: params[ln] for ln in stat_layers}
                rngs = jax.random.split(rng, iter_size)
                (stats, gsum, osum), _ = jax.lax.scan(
                    body, (stats0, zero_g, zero_o), (subs, rngs))
                grads = jax.tree_util.tree_map(
                    lambda g: g / iter_size, gsum)
                if exchange_on:
                    # ONE exchange per optimizer step, after the
                    # iter_size accumulation (Caffe's Normalize-then-
                    # exchange order)
                    grads = gs.exchange(grads, rng)
                outputs = {name: v / iter_size
                           for name, v in osum.items()}
                fwd_state = {ln: [stats[ln][bn] for bn, _, _ in
                                  net.param_layout[ln]]
                             for ln in stat_layers}
            lr = learning_rate(self.param, state.iter)
            params2, state2 = self._apply_update(params, grads, state, lr)
            # BatchNorm running stats updated by the forward pass(es)
            params2 = net.merge_forward_state(params2, fwd_state)
            outputs["lr"] = lr
            return params2, state2, outputs

        return step

    def jit_train_step(self):
        if self._jit_train_step is None:
            from .analysis.runtime import (maybe_guard_jit,
                                           maybe_poison_donation)
            fn = jax.jit(self.train_step_fn(), donate_argnums=(0, 1))
            fn = maybe_guard_jit(self._recompile_guard,
                                 "solver.train_step", fn, allow=1)
            self._jit_train_step = maybe_poison_donation(fn, (0, 1))
        return self._jit_train_step

    # ------------------------------------------------------------------
    def build_train_step_many(self, k: int):
        """Fused K-step train step: `jax.lax.scan` over a stacked
        `(K, batch…)` input block (axis 0 = the chunk axis, prepended
        to every input's per-step shape — time-major tops become
        (K, T, B, …)).

            (params, opt_state, stacked_inputs) -->
                (params', opt_state', stacked_outputs)

        One XLA program runs K solver iterations without returning to
        Python: the LR schedule, the iteration counter, gradient
        clipping and iter_size accumulation are already traced-friendly
        and advance on-device through the scan carry.  The per-step
        dropout/augment rng is derived INSIDE the scan as
        `fold_in(self.key, opt_state.iter)` — bit-identical to the
        host-side `step_rng(it)` stream, so a fused chunk reproduces K
        inline steps exactly (tests/test_steploop.py pins byte parity).
        Outputs come back stacked (K, …) per blob; `outputs['lr'][i]`
        is iteration i's learning rate."""
        if k < 1:
            raise ValueError(f"steps-per-loop k must be >= 1, got {k}")
        step = self.train_step_fn()
        key = self.key

        def fused(params: Params, state: OptState,
                  stacked: Dict[str, Array]):
            def body(carry, xs):
                p, s = carry
                rng = jax.random.fold_in(key, s.iter)
                p2, s2, out = step(p, s, xs, rng)
                return (p2, s2), out

            (p, s), outs = jax.lax.scan(body, (params, state), stacked,
                                        length=k)
            return p, s, outs

        return fused

    def jit_train_step_many(self, k: int):
        """Jitted fused K-step program, cached per k (the runtime only
        ever compiles the configured K; boundary remainders reuse the
        single-step program instead of compiling odd sizes)."""
        if k not in self._jit_train_step_many:
            from .analysis.runtime import (maybe_guard_jit,
                                           maybe_poison_donation)
            fn = jax.jit(self.build_train_step_many(k),
                         donate_argnums=(0, 1))
            fn = maybe_guard_jit(self._recompile_guard,
                                 f"solver.train_step_many[k={k}]",
                                 fn, allow=1)
            self._jit_train_step_many[k] = maybe_poison_donation(
                fn, (0, 1))
        return self._jit_train_step_many[k]

    # ------------------------------------------------------------------
    def eval_step_fn(self):
        """Validation forward — constructed by the shared blob-forward
        builder (serving/forward.py), so serving, batch extract, and
        validation trace one implementation."""
        net = self.test_net
        assert net is not None, "no TEST-phase net in this config"
        from .serving.forward import make_forward_fn
        return make_forward_fn(net, tuple(net.output_blobs))

    def jit_eval_step(self):
        if self._jit_eval_step is None:
            from .analysis.runtime import maybe_guard_jit
            self._jit_eval_step = maybe_guard_jit(
                self._recompile_guard, "solver.eval_step",
                jax.jit(self.eval_step_fn()), allow=1)
        return self._jit_eval_step

    # ------------------------------------------------------------------
    def step_rng(self, it: int) -> Array:
        """Per-iteration dropout/augment key, decorrelated by rank."""
        return jax.random.fold_in(self.key, it)

    @property
    def max_iter(self) -> int:
        return self.param.max_iter

    @property
    def test_interval(self) -> int:
        return self.param.test_interval

    @property
    def test_iter(self) -> int:
        return self.param.test_iter[0] if self.param.test_iter else 0
