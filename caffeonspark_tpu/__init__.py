"""CaffeOnSpark-TPU: a TPU-native deep learning framework with the
capabilities of yahoo/CaffeOnSpark, built on JAX/XLA/Pallas.

Subpackages:
  proto     — Caffe prototxt/protobuf schema + self-contained codec
  ops       — layer forward functions + fillers (+ Pallas kernels)
  parallel  — device mesh, data/tensor/sequence parallel strategies
  data      — data sources, transformer, LMDB/SequenceFile/Parquet readers
  models    — net compiler output, model zoo configs
  tools     — dataset conversion utilities (Binary2Sequence, Vocab, COCO)

Top-level modules mirror the reference's public API surface
(`CaffeOnSpark.scala`, `Config.scala`, `CaffeProcessor.scala`).
"""

__version__ = "0.1.0"
