"""HDF5Data source: Caffe's hdf5_data_layer.cpp semantics.

`hdf5_data_param.source` is a TEXT FILE listing .h5 paths (one per
line); each file carries one dataset per top blob, first axis = rows.
Shapes come from the first listed file (hdf5_data_layer.cpp
LoadHDF5FileData); no transform_param (Caffe forbids it on HDF5Data).
The reference never shipped an HDF5 CoS source (round-1 VERDICT
missing item 6) — this provides the layer end to end: shape probe for
net construction (net.py::data_layer_input_specs) + a DataSource that
feeds row batches.
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Dict, Iterator, List, Sequence, Tuple

import numpy as np

from .source import DataSource, _strip_scheme


def _file_list(list_path: str) -> List[str]:
    base = os.path.dirname(os.path.abspath(list_path))
    out = []
    with open(list_path) as f:
        for line in f:
            p = line.strip()
            if not p:
                continue
            if not os.path.isabs(p):
                p = os.path.join(base, p)
            out.append(p)
    if not out:
        raise ValueError(f"HDF5 source list {list_path} is empty")
    return out


# h5py surfaces corruption as a zoo of exception types (OSError,
# KeyError, IndexError on short datasets, RuntimeError, AttributeError
# on partially-parsed object headers) — converted to the data readers'
# one documented failure mode (ValueError) at the per-file read
# boundaries.  A genuine FileNotFoundError is re-raised untouched (a
# missing file is not a corrupt one — same rule as
# sequencefile._DECOMPRESS_ERRORS).
_H5_ERRORS = (OSError, KeyError, IndexError, RuntimeError,
              AttributeError)


@contextmanager
def _h5_boundary(path: str, what: str):
    try:
        yield
    except FileNotFoundError:
        raise
    except _H5_ERRORS as e:
        raise ValueError(f"{path}: corrupt/unreadable HDF5 {what}: "
                         f"{type(e).__name__}: {e}") from e


def hdf5_top_shapes(list_path: str, tops: Sequence[str],
                    batch_size: int) -> Dict[str, Tuple[int, ...]]:
    """(batch,) + per-row shape for each top, probed from the first
    file — the hdf5_data_layer.cpp top-sizing rule."""
    import h5py
    first = _file_list(_strip_scheme(list_path))[0]
    shapes: Dict[str, Tuple[int, ...]] = {}
    with _h5_boundary(first, "file"):
        with h5py.File(first, "r") as f:
            for top in tops:
                if top not in f:
                    raise ValueError(
                        f"dataset {top!r} missing from {first} "
                        f"(has: {sorted(f.keys())})")
                shapes[top] = (batch_size,) + tuple(f[top].shape[1:])
    return shapes


class HDF5Source(DataSource):
    """Yields (row_id, {top: row_array}) records; next_batch stacks."""

    def _batch_size(self) -> int:
        return int(self.layer.hdf5_data_param.batch_size)

    def source_uri(self) -> str:
        return _strip_scheme(self.layer.hdf5_data_param.source)

    def image_dims(self):  # not an image source
        raise NotImplementedError("HDF5Data has no image dims")

    def records(self) -> Iterator[tuple]:
        tops = list(self.layer.top)
        files = _file_list(self.source_uri())
        # rank sharding: round-robin whole files when possible, else
        # row-striping within the single file
        if len(files) >= self.num_ranks > 1:
            files = files[self.rank::self.num_ranks]
            stride, offset = 1, 0
        else:
            stride, offset = max(1, self.num_ranks), self.rank
        for path in files:
            yield from self._file_rows(path, tops, offset, stride)

    def _file_rows(self, path, tops, offset, stride):
        """One file's rows; ONLY the h5py read is wrapped (a missing
        list file or programming error must not be re-branded as
        data corruption)."""
        import h5py
        with _h5_boundary(path, "data"):
            with h5py.File(path, "r") as f:
                for t in tops:
                    if t not in f:
                        raise ValueError(
                            f"dataset {t!r} missing from {path} "
                            f"(has: {sorted(f.keys())})")
                counts = {t: f[t].shape[0] for t in tops}
                if len(set(counts.values())) > 1:
                    # hdf5_data_layer.cpp CHECKs equal num() across
                    # datasets — mismatched rows would otherwise leak
                    # an IndexError mid-epoch
                    raise ValueError(
                        f"{path}: datasets disagree on row count: "
                        f"{counts}")
                n = counts[tops[0]]
                arrays = {t: f[t] for t in tops}
                for i in range(offset, n, stride):
                    yield (f"{os.path.basename(path)}:{i}",
                           {t: np.asarray(arrays[t][i], np.float32)
                            for t in tops})

    def next_batch(self, records) -> Dict[str, np.ndarray]:
        tops = list(self.layer.top)
        return {t: np.stack([r[1][t] for r in records]).astype(
            np.float32) for t in tops}


# ---------------------------------------------------------------------------
# HDF5Output sink (hdf5_output_layer.cpp analog)
# ---------------------------------------------------------------------------

def collect_hdf5_outputs(forward_state: Dict) -> Dict[str, List]:
    """Pull the 'hdf5_output:<layer>' side-channel entries out of
    Net.apply's forward-state return: {layer_name: [bottom arrays]}."""
    prefix = "hdf5_output:"
    return {k[len(prefix):]: v for k, v in forward_state.items()
            if k.startswith(prefix)}


def write_hdf5_outputs(file_name: str, batches: Sequence[Sequence],
                       names: Sequence[str] = ("data", "label")) -> None:
    """Write accumulated HDF5Output batches to `file_name` with Caffe's
    dataset naming (hdf5_output_layer.cpp SaveBlobs: bottoms map to
    'data' and 'label'); batches are concatenated along axis 0."""
    import h5py
    if not batches:
        raise ValueError("no HDF5Output batches to write")
    n_bottoms = len(batches[0])
    with h5py.File(file_name, "w") as f:
        for i in range(n_bottoms):
            name = names[i] if i < len(names) else f"blob{i}"
            arr = np.concatenate(
                [np.asarray(b[i], np.float32) for b in batches], axis=0)
            f.create_dataset(name, data=arr)
