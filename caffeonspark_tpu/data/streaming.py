"""Streaming data source: follow a GROWING directory of part files.

The continuous-deployment loop (caffeonspark_tpu/deploy/) trains on
data that keeps arriving.  The filesystem contract is the one every
stream lands on disk with (Flume/Spark-streaming style): a writer
builds each part under a dot-prefixed temp name and `os.rename`s it
into place, so a part is either absent or complete — never half
readable.  `StreamingDirSource` re-lists the directory on `poll()`,
absorbs new parts, and serves **the data seen so far** as its record
set; "epoch" therefore means one pass over everything absorbed up to
the latest poll, and each fine-tune round's shuffled pass sees a
longer epoch than the previous round's.

Part formats (auto-detected per entry):
  * an LMDB part — a directory containing `data.mdb` (or a bare
    `*.mdb` file) of serialized Caffe `Datum` records, the same
    format the LMDB source reads;
  * a SequenceFile part — any other regular file, read through
    `SequenceFileReader` as (id, Datum) pairs.

Robustness: a poll that fails (transient listing/read error on flaky
shared storage, or an injected `COS_FAULT_FLAKY_STORAGE` fault from
`tools/chaos.py`) is retried with capped exponential backoff inside
the SAME poll call — bounded re-poll, the ParamStore retry posture —
and `wait_for_records` keeps re-polling until growth arrives or its
deadline passes, so a slow stream degrades to a skipped fine-tune
round rather than an error.

This is an ordinary `DataSource`: the PR 3 pipelined ingest
(`TransformerPool` ordered packing, `pack_batch`/`make_draw_fn`)
applies to it unchanged, and the deploy fine-tuner feeds through
`next_batch` exactly like the trainer.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Iterator, List, Optional, Tuple

from .lmdb_io import LmdbReader, LmdbWriter
from .sequencefile import SequenceFileReader
from .source import DataSource, ImageRecord, datum_to_record

_LOG = logging.getLogger(__name__)


def _is_part_name(name: str) -> bool:
    """Visible, committed entries only: dot/underscore prefixes are
    in-flight temp parts or markers (the rename-commit contract)."""
    return not name.startswith((".", "_"))


def _part_is_lmdb(path: str) -> bool:
    if os.path.isdir(path):
        return os.path.exists(os.path.join(path, "data.mdb"))
    return path.endswith(".mdb")


class _Part:
    """One committed, immutable part: path + cached record count."""

    __slots__ = ("path", "count")

    def __init__(self, path: str):
        self.path = path
        if _part_is_lmdb(path):
            with LmdbReader(path) as r:
                self.count = int(r.entries)
        else:
            self.count = sum(1 for _ in SequenceFileReader(path))

    def records(self) -> Iterator[ImageRecord]:
        if _part_is_lmdb(self.path):
            with LmdbReader(self.path) as r:
                for k, v in r.items(None, None):
                    yield datum_to_record(k, v)
        else:
            for key, val in SequenceFileReader(self.path):
                yield datum_to_record(key.encode("latin-1"), val)


class StreamingDirSource(DataSource):
    """Follow a growing part directory (source_class "StreamingDir").

    `records()` iterates everything absorbed by the last `poll()`;
    `poll()` absorbs newly committed parts (bounded retry on storage
    faults); `wait_for_records()` is the fine-tune trigger's bounded
    re-poll with capped exponential backoff."""

    POLL_ATTEMPTS = 8
    # a single entry that keeps failing across this many attempts is
    # QUARANTINED (skipped forever, warned once) — one corrupt part or
    # stray non-part file must not block absorption of everything
    # committed after it
    PART_STRIKES = 8

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._parts: List[_Part] = []
        self._seen: set = set()
        self._strikes: dict = {}
        self._broken: set = set()
        self.polls = 0
        self.poll_faults = 0
        # the first poll happens at construction so a pre-populated
        # directory serves immediately (later growth needs poll())
        self.poll()

    # -- stream following ---------------------------------------------
    def _list_parts(self, injector=None) -> List[str]:
        if injector is not None:
            injector.storage_fault()
        root = self.source_uri()
        if not os.path.isdir(root):
            return []
        return sorted(n for n in os.listdir(root) if _is_part_name(n))

    def poll(self, injector=None) -> int:
        """Absorb newly committed parts; returns how many RECORDS were
        added.  Transient listing/open failures (flaky storage — real
        or injected via the chaos layer) are retried with capped
        exponential backoff inside this call; a poll that stays broken
        past the attempt budget returns 0 (the stream tail is simply
        not visible yet — the caller's re-poll loop owns the deadline)."""
        self.polls += 1
        delay = 0.01
        # `added` accumulates ACROSS retry attempts: a fault that
        # lands mid-listing after some parts were already absorbed
        # must not lose their record count (the fine-tune trigger's
        # min_new growth check reads this return value)
        added = 0
        for attempt in range(self.POLL_ATTEMPTS):
            try:
                names = self._list_parts(injector)
            except (OSError, ValueError) as e:
                self.poll_faults += 1
                if attempt == self.POLL_ATTEMPTS - 1:
                    _LOG.warning(
                        "streaming poll failed %d times (%s) — "
                        "treating the tail as not yet visible",
                        self.POLL_ATTEMPTS, e)
                    return added
                time.sleep(delay)
                delay = min(delay * 2, 0.5)
                continue
            # absorb each pending part INDEPENDENTLY: one entry that
            # cannot be read (corrupt part, stray non-part file) must
            # not block the parts sorted after it.  Transient failures
            # retry on the next attempt; an entry that keeps failing
            # collects strikes (across polls too) and is quarantined.
            pending = [n for n in names if n not in self._seen
                       and n not in self._broken]
            failed_transient = False
            for name in pending:
                path = os.path.join(self.source_uri(), name)
                try:
                    part = _Part(path)
                except (OSError, ValueError) as e:
                    self.poll_faults += 1
                    self._strikes[name] = \
                        self._strikes.get(name, 0) + 1
                    if self._strikes[name] >= self.PART_STRIKES:
                        self._broken.add(name)
                        _LOG.warning(
                            "streaming: quarantining unreadable "
                            "entry %s after %d failures (%s) — "
                            "later parts keep absorbing", path,
                            self._strikes[name], e)
                    else:
                        failed_transient = True
                    continue
                self._parts.append(part)
                self._seen.add(name)
                added += part.count
            if not failed_transient:
                return added
            if attempt < self.POLL_ATTEMPTS - 1:
                time.sleep(delay)
                delay = min(delay * 2, 0.5)
        return added

    def wait_for_records(self, min_new: int = 1, *,
                         timeout_s: float = 30.0,
                         injector=None,
                         base_s: float = 0.02,
                         cap_s: float = 1.0) -> int:
        """Bounded re-poll with capped exponential backoff until at
        least `min_new` new records are visible; returns the number of
        new records absorbed (possibly 0 on timeout — the caller skips
        the round instead of failing)."""
        deadline = time.monotonic() + timeout_s
        total = self.poll(injector)
        delay = base_s
        while total < min_new and time.monotonic() < deadline:
            time.sleep(min(delay, max(0.0,
                                      deadline - time.monotonic())))
            delay = min(delay * 2, cap_s)
            total += self.poll(injector)
        return total

    # -- DataSource SPI -----------------------------------------------
    def records(self) -> Iterator[ImageRecord]:
        """Everything seen so far (snapshot of the parts list at call
        time — a concurrent poll() appending mid-iteration does not
        change this pass)."""
        for part in list(self._parts):
            yield from part.records()

    # -- reporting ----------------------------------------------------
    @property
    def total_records(self) -> int:
        return sum(p.count for p in self._parts)

    @property
    def part_count(self) -> int:
        return len(self._parts)

    def describe(self) -> dict:
        out = {"dir": self.source_uri(), "parts": self.part_count,
               "records": self.total_records, "polls": self.polls,
               "poll_faults": self.poll_faults}
        if self._broken:
            out["quarantined"] = sorted(self._broken)
        return out


# ---------------------------------------------------------------------------
# stream writer helpers (tests, bench, and operators seeding a stream)
# ---------------------------------------------------------------------------

def append_stream_part(stream_dir: str,
                       records: List[Tuple[bytes, bytes]],
                       name: Optional[str] = None) -> str:
    """Commit one immutable LMDB part atomically: build it under a
    dot-prefixed temp name, then `os.rename` into place — a reader's
    poll either sees the whole part or none of it."""
    os.makedirs(stream_dir, exist_ok=True)
    if name is None:
        existing = [n for n in os.listdir(stream_dir)
                    if _is_part_name(n)]
        name = f"part-{len(existing):05d}"
    tmp = os.path.join(stream_dir, f".tmp-{name}-{os.getpid()}")
    # pre-create the directory so LmdbWriter lays out <part>/data.mdb
    # (the LMDB-directory shape _part_is_lmdb detects after the rename)
    os.makedirs(tmp, exist_ok=True)
    LmdbWriter(tmp).write(records)
    final = os.path.join(stream_dir, name)
    os.rename(tmp, final)
    return final


def datum_records(images, labels,
                  start_id: int = 0) -> List[Tuple[bytes, bytes]]:
    """(N,C,H,W) float images in [0,1] + int labels → sorted LMDB
    (key, Datum bytes) records, 8-bit storage (the synthetic-dataset
    convention every drill and bench in this repo uses)."""
    import numpy as np

    from ..proto.caffe import Datum
    out = []
    for i in range(len(images)):
        img = images[i]
        c, h, w = img.shape
        out.append((b"%08d" % (start_id + i),
                    Datum(channels=c, height=h, width=w,
                          data=(img * 255).astype(np.uint8).tobytes(),
                          label=int(labels[i])).to_binary()))
    return out
