"""LMDB read/write without liblmdb: a memory-mapped B+tree reader and a
bulk (sorted, single-txn) writer for the on-disk format.

The reference reads Caffe LMDBs through lmdbjni inside a custom Spark RDD
(`caffe-grid/.../LmdbRDD.scala:97-155`: txn cursor iteration, key-range
partitioning :41-95).  This environment ships no lmdb binding, so the
format itself is implemented here:

  * ``LmdbReader`` — mmap the data file, locate the live meta page
    (higher txnid of pages 0/1), walk the main DB's B+tree; supports
    full scans, ``seek(key)``, and key-range partitioning for the
    LmdbRDD-style sharded read.
  * ``LmdbWriter`` — bottom-up bulk build of leaf/branch/overflow pages
    from sorted records + twin meta pages; produces files this reader
    (and liblmdb) can open.  Used by tools (Sequence→LMDB) and test
    fixtures (the setup-mnist.sh analog).

Format notes (64-bit layout): 16-byte page header {pgno u64, pad u16,
flags u16, lower u16, upper u16}; meta page = header + {magic 0xBEEFC0DE,
version 1, address, mapsize, dbs[2] (48B each: pad/flags/depth/branch/
leaf/overflow/entries/root — dbs[0].pad doubles as the page size),
last_pg, txnid}; leaf/branch nodes = {lo u16, hi u16, flags u16,
ksize u16, key..., data...} with node offsets in a u16 array after the
header; branch pgno packed in lo|hi<<16|flags<<32; F_BIGDATA (0x01)
nodes store an 8-byte overflow pgno instead of inline data.
"""

from __future__ import annotations

import mmap
import os
import struct
from typing import Iterator, List, Optional, Tuple

MAGIC = 0xBEEFC0DE
VERSION = 1

P_BRANCH = 0x01
P_LEAF = 0x02
P_OVERFLOW = 0x04
P_META = 0x08

F_BIGDATA = 0x01

PAGE_HDR = 16
META_OFF = PAGE_HDR  # MDB_meta starts after the page header


def _db_record(buf, off) -> dict:
    pad, flags, depth = struct.unpack_from("<IHH", buf, off)
    branch, leaf, overflow, entries, root = struct.unpack_from(
        "<QQQQQ", buf, off + 8)
    return dict(pad=pad, flags=flags, depth=depth, branch=branch,
                leaf=leaf, overflow=overflow, entries=entries, root=root)


class LmdbReader:
    """Read-only scan/seek over an LMDB main database."""

    def __init__(self, path: str):
        if os.path.isdir(path):
            path = os.path.join(path, "data.mdb")
        self.path = path
        self._f = open(path, "rb")
        try:
            self._map = mmap.mmap(self._f.fileno(), 0,
                                  access=mmap.ACCESS_READ)
        except ValueError as e:           # empty file
            self._f.close()
            raise ValueError(f"{path}: not an LMDB data file: {e}") \
                from e
        try:
            self._read_meta(path)
        except (struct.error, IndexError, OverflowError) as e:
            self.close()
            raise self._corrupt(e) from e
        except BaseException:     # bad magic etc. — no fd/mmap leak
            self.close()
            raise

    def _corrupt(self, e: BaseException) -> ValueError:
        """Malformed files surface as ValueError — the readers' one
        documented failure mode (mirrors proto.descriptor); a corrupt
        byte must never leak struct.error or recurse forever."""
        return ValueError(f"{self.path}: corrupt LMDB file: "
                          f"{type(e).__name__}: {e}")

    def _read_meta(self, path: str) -> None:
        m = self._map
        metas = []
        for pg in (0, 1):
            off = pg * 4096 + META_OFF  # meta pages are at most 4096 apart?
            # page size unknown before reading meta; try offset with the
            # minimum page size first, re-derive after
            magic, version = struct.unpack_from("<II", m, off)
            if magic != MAGIC:
                continue
            dbs0 = _db_record(m, off + 24)
            psize = dbs0["pad"] or 4096
            main = _db_record(m, off + 72)
            last_pg, txnid = struct.unpack_from("<QQ", m, off + 120)
            metas.append((txnid, psize, main))
        if not metas:
            raise ValueError(f"{path}: not an LMDB data file (bad magic)")
        metas.sort()
        txnid, self.psize, self.main = metas[-1]
        # page-1 meta lives at offset psize, not 4096 — re-read if needed
        if self.psize != 4096:
            metas = []
            for pg in (0, 1):
                off = pg * self.psize + META_OFF
                magic, version = struct.unpack_from("<II", m, off)
                if magic != MAGIC:
                    continue
                main = _db_record(m, off + 72)
                _, txnid = struct.unpack_from("<QQ", m, off + 120)
                metas.append((txnid, main))
            metas.sort()
            self.main = metas[-1][1]
        self.entries = int(self.main["entries"])

    def close(self):
        self._map.close()
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    # -- page access -------------------------------------------------------

    def _page(self, pgno: int) -> Tuple[int, int, int, int]:
        """Returns (base_offset, flags, lower, upper)."""
        base = pgno * self.psize
        _, _, flags, lower, upper = struct.unpack_from(
            "<QHHHH", self._map, base)
        return base, flags, lower, upper

    def _num_keys(self, lower: int) -> int:
        return (lower - PAGE_HDR) // 2

    def _node(self, base: int, idx: int) -> int:
        (ptr,) = struct.unpack_from("<H", self._map,
                                    base + PAGE_HDR + 2 * idx)
        return base + ptr

    def _leaf_kv(self, base: int, idx: int) -> Tuple[bytes, bytes]:
        m = self._map
        noff = self._node(base, idx)
        lo, hi, flags, ksize = struct.unpack_from("<HHHH", m, noff)
        dsize = lo | (hi << 16)
        key = bytes(m[noff + 8:noff + 8 + ksize])
        if flags & F_BIGDATA:
            (opgno,) = struct.unpack_from("<Q", m, noff + 8 + ksize)
            obase = opgno * self.psize
            data = bytes(m[obase + PAGE_HDR:obase + PAGE_HDR + dsize])
        else:
            doff = noff + 8 + ksize
            data = bytes(m[doff:doff + dsize])
        return key, data

    def _branch_child(self, base: int, idx: int) -> Tuple[bytes, int]:
        m = self._map
        noff = self._node(base, idx)
        lo, hi, flags, ksize = struct.unpack_from("<HHHH", m, noff)
        pgno = lo | (hi << 16) | (flags << 32)
        key = bytes(m[noff + 8:noff + 8 + ksize])
        return key, pgno

    # -- iteration ---------------------------------------------------------

    def items(self, start_key: Optional[bytes] = None,
              stop_key: Optional[bytes] = None
              ) -> Iterator[Tuple[bytes, bytes]]:
        """Sorted (key, value) pairs in [start_key, stop_key)."""
        root = int(self.main["root"])
        if root == 2 ** 64 - 1:  # P_INVALID: empty db
            return
        try:
            yield from self._walk(root, start_key, stop_key, set())
        except (struct.error, IndexError, OverflowError,
                RecursionError) as e:
            raise self._corrupt(e) from e

    def _walk(self, pgno, start_key, stop_key, seen):
        if pgno in seen:
            # a corrupted child pointer forming a page cycle would
            # otherwise recurse/loop forever
            raise ValueError(
                f"{self.path}: corrupt LMDB file: page cycle at "
                f"pgno {pgno}")
        seen.add(pgno)
        base, flags, lower, upper = self._page(pgno)
        n = self._num_keys(lower)
        if flags & P_LEAF:
            for i in range(n):
                k, v = self._leaf_kv(base, i)
                if start_key is not None and k < start_key:
                    continue
                if stop_key is not None and k >= stop_key:
                    return
                yield k, v
        elif flags & P_BRANCH:
            for i in range(n):
                _, child = self._branch_child(base, i)
                # subtree key range pruning via separator keys
                if start_key is not None and i + 1 < n:
                    nxt_key, _ = self._branch_child(base, i + 1)
                    if nxt_key and nxt_key <= start_key:
                        continue
                if stop_key is not None and i > 0:
                    this_key, _ = self._branch_child(base, i)
                    if this_key and this_key >= stop_key:
                        return
                yield from self._walk(child, start_key, stop_key, seen)
        else:
            raise ValueError(f"unexpected page flags {flags:#x}")

    def keys(self) -> Iterator[bytes]:
        for k, _ in self.items():
            yield k

    def partition_ranges(self, num_partitions: int
                         ) -> List[Tuple[Optional[bytes], Optional[bytes]]]:
        """Split the key space into ~equal ranges (LmdbRDD.scala:41-95
        analog: scan keys, emit [start, stop) bounds per partition)."""
        if num_partitions <= 1:
            return [(None, None)]
        ks = list(self.keys())
        n = num_partitions
        bounds: List[Tuple[Optional[bytes], Optional[bytes]]] = []
        # exactly n ranges, each rank a DISTINCT (possibly empty) slice:
        # an empty range is (k, k) — items() is [start, stop) so it
        # yields nothing — rather than being dropped, which would alias
        # ranks onto the same keys via `rank % len(ranges)`
        for i in range(n):
            si = i * len(ks) // n
            ei = (i + 1) * len(ks) // n
            if si >= ei:
                k0 = ks[0] if ks else b""
                bounds.append((k0, k0))
                continue
            lo = None if si == 0 else ks[si]
            hi = None if ei >= len(ks) else ks[ei]
            bounds.append((lo, hi))
        return bounds


class LmdbWriter:
    """Bulk-build an LMDB file from sorted (key, value) records."""

    def __init__(self, path: str, psize: int = 4096):
        if os.path.isdir(path) or path.endswith(os.sep) or "." not in \
                os.path.basename(path):
            os.makedirs(path, exist_ok=True)
            path = os.path.join(path, "data.mdb")
        self.path = path
        self.psize = psize
        self._pages: List[bytes] = []  # data pages, pgno = index + 2

    # node byte size (8-byte header + key + inline data, even-aligned)
    def _leaf_node(self, key: bytes, data: bytes, *,
                   overflow_pgno: Optional[int] = None) -> bytes:
        if overflow_pgno is None:
            body = struct.pack("<HHHH", len(data) & 0xFFFF,
                               len(data) >> 16, 0, len(key)) + key + data
        else:
            body = struct.pack("<HHHH", len(data) & 0xFFFF,
                               len(data) >> 16, F_BIGDATA, len(key)) \
                + key + struct.pack("<Q", overflow_pgno)
        if len(body) % 2:
            body += b"\x00"
        return body

    def _branch_node(self, key: bytes, pgno: int) -> bytes:
        body = struct.pack("<HHHH", pgno & 0xFFFF, (pgno >> 16) & 0xFFFF,
                           (pgno >> 32) & 0xFFFF, len(key)) + key
        if len(body) % 2:
            body += b"\x00"
        return body

    def _flush_page(self, flags: int, nodes: List[bytes]) -> int:
        """Pack nodes into one page; returns pgno."""
        psize = self.psize
        pgno = len(self._pages) + 2
        ptrs = []
        off = psize
        payload = bytearray(psize)
        for nb in nodes:
            off -= len(nb)
            payload[off:off + len(nb)] = nb
            ptrs.append(off)
        lower = PAGE_HDR + 2 * len(nodes)
        assert lower <= off, "page overflow"
        struct.pack_into("<QHHHH", payload, 0, pgno, 0, flags, lower, off)
        for i, p in enumerate(ptrs):
            struct.pack_into("<H", payload, PAGE_HDR + 2 * i, p)
        self._pages.append(bytes(payload))
        return pgno

    def _flush_overflow(self, data: bytes) -> int:
        psize = self.psize
        pgno = len(self._pages) + 2
        npages = (PAGE_HDR + len(data) + psize - 1) // psize
        buf = bytearray(npages * psize)
        struct.pack_into("<QHHI", buf, 0, pgno, 0, P_OVERFLOW, npages)
        buf[PAGE_HDR:PAGE_HDR + len(data)] = data
        for i in range(npages):
            self._pages.append(bytes(buf[i * psize:(i + 1) * psize]))
        return pgno

    def write(self, records: List[Tuple[bytes, bytes]]) -> None:
        records = sorted(records)
        psize = self.psize
        max_inline = (psize - PAGE_HDR) // 2 - 16  # conservative node cap
        leaf_stats = dict(leaf=0, overflow=0, branch=0)

        # ---- leaves ----
        level: List[Tuple[bytes, int]] = []  # (first_key, pgno)
        nodes: List[bytes] = []
        used = PAGE_HDR
        first_key = None
        for k, v in records:
            if len(v) + len(k) + 8 > max_inline:
                opg = self._flush_overflow(v)
                leaf_stats["overflow"] += 1
                nb = self._leaf_node(k, v, overflow_pgno=opg)
            else:
                nb = self._leaf_node(k, v)
            if nodes and used + len(nb) + 2 > psize:
                pg = self._flush_page(P_LEAF, nodes)
                leaf_stats["leaf"] += 1
                level.append((first_key, pg))
                nodes, used, first_key = [], PAGE_HDR, None
            if first_key is None:
                first_key = k
            nodes.append(nb)
            used += len(nb) + 2
        if nodes:
            pg = self._flush_page(P_LEAF, nodes)
            leaf_stats["leaf"] += 1
            level.append((first_key, pg))

        # ---- branches (bottom-up) ----
        depth = 1
        while len(level) > 1:
            nxt: List[Tuple[bytes, int]] = []
            nodes, used, first_key = [], PAGE_HDR, None
            for i, (k, pg) in enumerate(level):
                bk = b"" if not nodes else k  # leftmost branch key empty
                nb = self._branch_node(bk, pg)
                if nodes and used + len(nb) + 2 > psize:
                    bpg = self._flush_page(P_BRANCH, nodes)
                    leaf_stats["branch"] += 1
                    nxt.append((first_key, bpg))
                    nodes, used = [], PAGE_HDR
                    nb = self._branch_node(b"", pg)
                    first_key = k
                if first_key is None:
                    first_key = k
                nodes.append(nb)
                used += len(nb) + 2
            if nodes:
                bpg = self._flush_page(P_BRANCH, nodes)
                leaf_stats["branch"] += 1
                nxt.append((first_key, bpg))
            level = nxt
            depth += 1

        root = level[0][1] if level else 2 ** 64 - 1
        if not records:
            depth = 0

        # ---- metas ----
        last_pg = len(self._pages) + 1
        mapsize = (last_pg + 1) * psize

        def meta(txnid: int) -> bytes:
            buf = bytearray(psize)
            struct.pack_into("<QHHHH", buf, 0, txnid & 1, 0, P_META, 0, 0)
            o = META_OFF
            struct.pack_into("<II", buf, o, MAGIC, VERSION)
            struct.pack_into("<QQ", buf, o + 8, 0, mapsize)
            # dbs[0] (free db): pad carries psize
            struct.pack_into("<IHH", buf, o + 24, psize, 0, 0)
            struct.pack_into("<QQQQQ", buf, o + 32, 0, 0, 0, 0,
                             2 ** 64 - 1)
            # dbs[1] (main db)
            struct.pack_into("<IHH", buf, o + 72, 0, 0, depth)
            struct.pack_into("<QQQQQ", buf, o + 80,
                             leaf_stats["branch"], leaf_stats["leaf"],
                             leaf_stats["overflow"], len(records), root)
            struct.pack_into("<QQ", buf, o + 120, last_pg, txnid)
            return bytes(buf)

        with open(self.path, "wb") as f:
            f.write(meta(0))
            f.write(meta(1))
            for p in self._pages:
                f.write(p)
