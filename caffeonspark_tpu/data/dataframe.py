"""DataFrameSource: generic multi-column Parquet → CoSData typed tops.

Reference: `caffe-grid/.../DataFrameSource.scala` (Top class :315-353,
nextBatch packing :225-302): each `cos_data_param.top {}` names a column
with a type in {STRING, INT, FLOAT, INT_ARRAY, FLOAT_ARRAY, RAW_IMAGE,
ENCODED_IMAGE, ENCODED_IMAGE_WITH_DIM}, per-top transform params, and
`transpose: true` producing time-major (T, B) layouts for recurrent nets
(`cos_data_layer.cpp:35-41`).
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, Sequence

import numpy as np

from ..proto.caffe import TopBlobType as T
from .source import DataSource, decode_image
from .transformer import Transformer


class DataFrameSource(DataSource):

    def __init__(self, layer, **kw):
        super().__init__(layer, **kw)
        self.tops = list(layer.cos_data_param.top)
        self.top_transformers = {}
        for top in self.tops:
            if top.has("transform_param"):
                self.top_transformers[top.name] = Transformer(
                    top.transform_param, phase_train=self.phase_train,
                    seed=self.seed + self.rank,
                    mean_dir=os.path.dirname(self.source_uri()) or None)

    def image_dims(self):
        for top in self.tops:
            if top.type in (T.RAW_IMAGE, T.ENCODED_IMAGE,
                            T.ENCODED_IMAGE_WITH_DIM):
                return (int(top.channels), int(top.height), int(top.width))
        return (0, 0, 0)

    # -- rows --------------------------------------------------------------
    def rows(self) -> Iterator[Dict]:
        fmt = self.layer.cos_data_param.dataframe_format or "parquet"
        path = self.source_uri()
        if fmt == "parquet":
            import pyarrow.parquet as pq
            table = pq.read_table(path)
        elif fmt == "json":
            import pyarrow.json as pj
            table = pj.read_json(path)
        else:
            raise ValueError(f"dataframe_format {fmt!r}")
        n = table.num_rows
        lo = self.rank * n // self.num_ranks
        hi = (self.rank + 1) * n // self.num_ranks
        d = table.slice(lo, hi - lo).to_pydict()
        names = table.column_names
        for i in range(hi - lo):
            yield {c: d[c][i] for c in names}

    def records(self):
        # SPI compat: yield rows (typed packing happens in next_batch)
        return self.rows()

    # -- packing -----------------------------------------------------------
    def _pack_top(self, top, values: Sequence) -> np.ndarray:
        b = len(values)
        t = top.type
        if t == T.INT or t == T.FLOAT:
            arr = np.asarray([float(v if v is not None else 0)
                              for v in values], np.float32)
            return arr.reshape(b, 1, 1, 1)
        if t in (T.INT_ARRAY, T.FLOAT_ARRAY):
            width = int(top.channels)
            out = np.zeros((b, width), np.float32)
            for i, v in enumerate(values):
                v = list(v or [])[:width]
                out[i, :len(v)] = v
            if top.transpose:
                return np.ascontiguousarray(out.T)   # (T, B) time-major
            return out
        if t == T.STRING:
            return np.asarray([str(v) for v in values], object)
        # image types
        c, h, w = int(top.channels), int(top.height), int(top.width)
        oh = int(top.out_height or h)
        ow = int(top.out_width or w)
        imgs = np.zeros((b, c, oh, ow), np.float32)
        for i, v in enumerate(values):
            payload = bytes(v) if isinstance(v, (bytes, bytearray)) \
                else bytes(v or [])
            if t == T.RAW_IMAGE:
                imgs[i] = np.frombuffer(payload, np.uint8).astype(
                    np.float32).reshape(c, h, w)[:, :oh, :ow]
            else:  # ENCODED_IMAGE / ENCODED_IMAGE_WITH_DIM
                imgs[i] = decode_image(payload, channels=c,
                                       resize_hw=(oh, ow))
        tr = self.top_transformers.get(top.name)
        if tr is not None:
            imgs = tr(imgs)
        return imgs

    def next_batch(self, rows: Sequence[Dict]) -> Dict[str, np.ndarray]:
        out: Dict[str, np.ndarray] = {}
        for top in self.tops:
            col = top.name
            vals = [r.get(col) for r in rows]
            out[col] = self._pack_top(top, vals)
        return out

    # batches() comes from the DataSource base: records() returns rows()
    # here, so the shared shuffle/epoch logic applies unchanged.
