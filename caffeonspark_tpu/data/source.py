"""Data sources: the DataSource SPI of the reference re-expressed for a
host→TPU feed pipeline.

Reference: `caffe-grid/.../DataSource.scala:27-128` (SPI: init /
makeRDD / nextBatch / STOP_MARK queue protocol) with concrete sources
LMDB (`LMDB.scala`), SeqImageDataSource (`SeqImageDataSource.scala`),
ImageDataFrame (`ImageDataFrame.scala`), DataFrameSource
(`DataFrameSource.scala`) — all instantiated reflectively from the
prototxt `source_class` field (`DataSource.scala:133-166`).

Here each source yields **record tuples** `(id, label, C, H, W, encoded,
bytes)` — the reference's 7-tuple RDD element — and `next_batch` packs
them through the `Transformer` into the data layer's named blobs, ready
for `jax.device_put`.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..proto.caffe import Datum, LayerParameter
from .lmdb_io import LmdbReader
from .sequencefile import SequenceFileReader
from .transformer import AugDraw, DEVICE_AUX_SUFFIX, Transformer

ImageRecord = Tuple[str, float, int, int, int, bool, bytes]

STOP_MARK = object()


def _strip_scheme(uri: str) -> str:
    for scheme in ("file:", "hdfs:"):
        if uri.startswith(scheme):
            uri = uri[len(scheme):]
    return uri


def decode_image(data: bytes, *, channels: int,
                 resize_hw: Optional[Tuple[int, int]] = None) -> np.ndarray:
    """JPEG/PNG bytes → (C, H, W) float32, BGR channel order like OpenCV
    (`jcaffe/Mat.java decode` semantics)."""
    import cv2
    flag = cv2.IMREAD_GRAYSCALE if channels == 1 else cv2.IMREAD_COLOR
    img = cv2.imdecode(np.frombuffer(data, np.uint8), flag)
    if img is None:
        raise ValueError("image decode failed")
    if resize_hw is not None and (img.shape[0], img.shape[1]) != resize_hw:
        img = cv2.resize(img, (resize_hw[1], resize_hw[0]))
    if img.ndim == 2:
        img = img[:, :, None]
    return img.transpose(2, 0, 1).astype(np.float32)


def datum_to_record(key: bytes, raw: bytes) -> ImageRecord:
    """LMDB value (serialized Datum) → 7-tuple record
    (`LmdbRDD.scala:136-151` + CHW ordering :270-281)."""
    d = Datum.from_binary(raw)
    if not d.encoded and not d.has("data") and d.float_data:
        # float-payload Datum (e.g. feature LMDBs): raw float32 planes,
        # not image bytes — pass through as an ndarray payload
        arr = np.asarray(list(d.float_data), np.float32).reshape(
            d.channels, d.height, d.width)
        return (key.decode("latin-1"), float(d.label), d.channels,
                d.height, d.width, False, arr)
    if d.encoded or not d.has("data"):
        data = d.data if d.has("data") else b""
        return (key.decode("latin-1"), float(d.label), d.channels,
                d.height, d.width, True, data)
    return (key.decode("latin-1"), float(d.label), d.channels, d.height,
            d.width, False, d.data)


class DataSource:
    """SPI base: concrete sources implement `records()`."""

    def __init__(self, layer: LayerParameter, *, phase_train: bool,
                 rank: int = 0, num_ranks: int = 1, seed: int = 0,
                 resize: bool = False, num_threads: int = 0):
        self.layer = layer
        self.phase_train = phase_train
        self.rank = rank
        self.num_ranks = num_ranks
        self.seed = seed
        self.resize = resize
        self.num_threads = num_threads  # 0 = native decoder's default
        self.batch_size = self._batch_size()
        self.transformer = Transformer(
            layer.transform_param if layer.has("transform_param") else None,
            phase_train=phase_train, seed=seed + rank,
            mean_dir=os.path.dirname(self.source_uri()) or None)
        self._device_transform = False
        self._device_fns = None

    # -- config ------------------------------------------------------------
    def _batch_size(self) -> int:
        if self.layer.has("memory_data_param"):
            return int(self.layer.memory_data_param.batch_size)
        if self.layer.has("cos_data_param"):
            return int(self.layer.cos_data_param.batch_size)
        raise ValueError("data layer has no batch size")

    def source_uri(self) -> str:
        if self.layer.has("memory_data_param"):
            return _strip_scheme(self.layer.memory_data_param.source)
        if self.layer.has("cos_data_param"):
            return _strip_scheme(self.layer.cos_data_param.source)
        return ""

    def image_dims(self) -> Tuple[int, int, int]:
        p = self.layer.memory_data_param
        return int(p.channels), int(p.height), int(p.width)

    # -- SPI ---------------------------------------------------------------
    def records(self) -> Iterator[ImageRecord]:
        raise NotImplementedError

    def record_partitions(self, n: int) -> List[Any]:
        """Opaque partition descriptors for sharded reads (rank i of n)."""
        return list(range(n))

    def next_batch(self, records: Sequence[ImageRecord],
                   draw: Optional[AugDraw] = None
                   ) -> Dict[str, np.ndarray]:
        """Pack + transform records into the data layer's blobs
        (ImageDataSource.nextBatch analog, `ImageDataSource.scala:99-163`).
        All-encoded batches take the native threaded JPEG path
        (libcos_native, the jcaffe Mat/decode analog) when built.
        `draw` replays a pre-drawn augmentation (TransformerPool's
        ordered-draw protocol) instead of consuming the RNG here."""
        c, h, w = self.image_dims()
        n = len(records)
        labels = np.asarray([r[1] for r in records], np.float32)
        if all(r[5] for r in records):
            data = self._decode_encoded_batch(records, c, h, w)
        else:
            data = np.zeros((n, c, h, w), np.float32)
            for i, (rid, label, rc, rh, rw, encoded, payload) in \
                    enumerate(records):
                if encoded:
                    data[i] = decode_image(
                        payload, channels=c,
                        resize_hw=(h, w) if (self.resize
                                             or (rh, rw) != (h, w))
                        else None)
                else:
                    if (rh, rw) != (h, w):
                        raise ValueError(
                            f"record {rid}: {rh}x{rw} != layer {h}x{w} "
                            "(set -resize for encoded sources)")
                    if isinstance(payload, np.ndarray):
                        data[i] = payload.reshape(rc, rh, rw)
                    else:
                        data[i] = np.frombuffer(payload, np.uint8).astype(
                            np.float32).reshape(rc, rh, rw)
        out_names = list(self.layer.top)
        # device-transform split: ships uint8 + per-sample crop/flip aux.
        # Requires pixel payloads (encoded image or uint8 buffer) — a
        # float payload can't be losslessly narrowed, and a silent
        # per-batch fallback would emit inconsistent key sets that
        # combine_batches/iter_size would mis-merge, so fail fast.
        if self._device_transform:
            bad = next((r for r in records
                        if not r[5] and isinstance(r[6], np.ndarray)
                        and r[6].dtype != np.uint8), None)
            if bad is not None:
                raise ValueError(
                    f"COS_DEVICE_TRANSFORM=1 needs uint8/encoded pixel "
                    f"payloads, but record {bad[0]!r} carries "
                    f"{bad[6].dtype} data — unset COS_DEVICE_TRANSFORM "
                    "for float-valued sources")
            u8, aux = self.transformer.host_stage(data, draw=draw)
            batch = {out_names[0]: u8,
                     out_names[0] + DEVICE_AUX_SUFFIX: aux}
        else:
            batch = {out_names[0]: self.transformer(data, draw=draw)}
        if len(out_names) > 1:
            batch[out_names[1]] = labels
        return batch

    # -- transformer-pool protocol ------------------------------------
    def pack_batch(self, records: Sequence[ImageRecord],
                   draw: Optional[AugDraw] = None
                   ) -> Dict[str, np.ndarray]:
        """next_batch with an optional ordered pre-draw — the callable
        TransformerPool workers run.  Sources that override next_batch
        (HDF5/DataFrame blob packing) never get a draw (make_draw_fn
        returns None for them), so their signature stays untouched."""
        if draw is None:
            return self.next_batch(records)
        return self.next_batch(records, draw=draw)

    def make_draw_fn(self):
        """Per-batch augmentation pre-draw `fn(n) -> AugDraw` for the
        pool dispatcher, consuming the transformer RNG in FEED ORDER on
        one thread so `num_threads > 1` packing reproduces the inline
        path's augmentation stream.  None when this source packs its
        own blobs or has no static image geometry — those pack without
        a pre-draw (transformer draws under its own lock)."""
        if type(self).next_batch is not DataSource.next_batch:
            return None
        try:
            c, h, w = self.image_dims()
        except Exception:       # noqa: BLE001 — geometry-less source
            return None
        t = self.transformer
        return lambda n: t.draw(n, h, w)

    def enable_device_transform(self, net_dtype=None):
        """Opt in to the uint8-infeed transform split: when
        COS_DEVICE_TRANSFORM=1 and this source supports it, next_batch
        emits uint8 pixels + aux offsets and the returned {top: jit-able
        fn} runs mean/scale on the device (Transformer.device_stage_fn).
        The whole policy lives here — env gate, out-dtype rule (bf16
        nets get device-side cast, f32 nets stay f32), and the
        host-path fallbacks: returns None for sources that override
        next_batch with their own blob packing (HDF5/DataFrame), have
        no image geometry, or use an unsupported mean shape."""
        import os
        if os.environ.get("COS_DEVICE_TRANSFORM") != "1":
            return None
        if type(self).next_batch is not DataSource.next_batch:
            return None
        try:
            c, h, w = self.image_dims()
        except (NotImplementedError, ValueError):
            return None
        if not self.transformer.device_eligible(h, w):
            return None
        import jax
        import jax.numpy as jnp
        out_dtype = None if net_dtype in (None, jnp.float32) else net_dtype
        self._device_transform = True
        fns = {self.layer.top[0]:
               self.transformer.device_stage_fn(out_dtype)}
        # jitted copies for direct consumers (apply_device_stage);
        # device_prefetch jits the raw fns itself
        self._device_fns = {k: jax.jit(f) for k, f in fns.items()}
        return fns

    def apply_device_stage(self, batch, shardings=None):
        """Finish the split for consumers that call next_batch directly
        (validation rounds, feature extraction) instead of feeding
        through device_prefetch: run the jitted device stage on any
        uint8+aux tops.  `shardings` ({top: NamedSharding}) places the
        uint8/aux arrays BEFORE the stage so the output matches a
        sharded step's in_shardings.  No-op when the split is off."""
        if not self._device_transform \
                or not getattr(self, "_device_fns", None):
            return batch
        import jax
        out = dict(batch)
        for k, f in self._device_fns.items():
            aux = out.pop(k + DEVICE_AUX_SUFFIX, None)
            if aux is None:
                continue
            v = out[k]
            if shardings is not None and k in shardings:
                sh = shardings[k]
                if jax.process_count() > 1:
                    # multi-host: assemble the global array from this
                    # process's local shard (device_put can't target
                    # non-addressable devices) — same rule as
                    # queue_runner.device_prefetch's put_one
                    v = jax.make_array_from_process_local_data(sh, v)
                    aux = jax.make_array_from_process_local_data(sh, aux)
                else:
                    v = jax.device_put(v, sh)
                    aux = jax.device_put(aux, sh)
            out[k] = f(v, aux)
        return out

    def _decode_encoded_batch(self, records, c, h, w) -> np.ndarray:
        from .. import native
        # under the device-transform split the native decoder writes
        # uint8 planes directly — no float buffer, no host cast pass
        dt = np.uint8 if self._device_transform else np.float32
        if native.available():
            try:
                return native.decode_batch(
                    [r[6] for r in records], channels=c, out_h=h,
                    out_w=w, num_threads=self.num_threads,
                    out_dtype=dt)
            except ValueError:
                pass  # corrupt image somewhere: per-image path reports it
        n = len(records)
        data = np.zeros((n, c, h, w), np.float32)
        for i, r in enumerate(records):
            data[i] = decode_image(r[6], channels=c, resize_hw=(h, w))
        return data

    SHUFFLE_BUFFER = 4096

    def epoch_seed(self, epoch: int) -> int:
        """Deterministic per-(seed, rank, epoch) shuffle seed — shared
        by the streaming shuffle and the -persistent cache reshuffle so
        both modes see the same epoch orders."""
        return (self.seed + self.rank * 9973
                + epoch * 131071) & 0x7FFFFFFF

    def shuffled_records(self, epoch: int) -> Iterator[ImageRecord]:
        """Streaming shuffle over records(): a bounded reservoir buffer
        (capacity SHUFFLE_BUFFER) emits a random resident element as
        each new record arrives — order varies per epoch and per rank
        but is fully determined by (seed, rank, epoch).  The reference
        gets its shuffling from randomized LMDB keys + Spark partition
        order; a streaming buffer is the TPU-feed equivalent."""
        rng = np.random.RandomState(self.epoch_seed(epoch))
        buf: List[ImageRecord] = []
        for rec in self.records():
            if len(buf) < self.SHUFFLE_BUFFER:
                buf.append(rec)
                continue
            j = rng.randint(0, len(buf))
            out, buf[j] = buf[j], rec
            yield out
        rng.shuffle(buf)
        yield from buf

    def batches(self, *, loop: bool = True,
                shuffle: Optional[bool] = None
                ) -> Iterator[Dict[str, np.ndarray]]:
        """Convenience: records → transformed batches, epoch-looping.
        Shuffles by default in the TRAIN phase."""
        if shuffle is None:
            shuffle = self.phase_train
        buf: List[ImageRecord] = []
        epoch = 0
        while True:
            got_any = False
            records = (self.shuffled_records(epoch) if shuffle
                       else self.records())
            for rec in records:
                got_any = True
                buf.append(rec)
                if len(buf) == self.batch_size:
                    yield self.next_batch(buf)
                    buf = []
            if not got_any:
                return
            if not loop:
                if buf:
                    yield self.next_batch(buf)
                return
            epoch += 1


class _DBSource(DataSource):
    """Shared rank-sharded read loop for key-value databases of Datum
    records; subclasses provide `_reader()`."""

    def _reader(self):
        raise NotImplementedError

    def records(self) -> Iterator[ImageRecord]:
        with self._reader() as r:
            ranges = r.partition_ranges(self.num_ranks)
            lo, hi = ranges[self.rank % len(ranges)]
            for k, v in r.items(lo, hi):
                yield datum_to_record(k, v)


class LMDB(_DBSource):
    """LMDB of Caffe Datum records (source_class com.yahoo.ml.caffe.LMDB)."""

    def _reader(self):
        return LmdbReader(self.source_uri())


class CaffeDataSource(_DBSource):
    """Caffe's own `Data` layer (`data_param { source backend }`):
    LMDB or LEVELDB databases of serialized Datum records — the
    db_lmdb.cpp / db_leveldb.cpp pair.  Geometry comes from the first
    record (Caffe infers shapes from the database the same way)."""

    def _batch_size(self) -> int:
        return int(self.layer.data_param.batch_size)

    def source_uri(self) -> str:
        return _strip_scheme(self.layer.data_param.source)

    def _reader(self):
        from ..proto.caffe import DBBackend
        if self.layer.data_param.backend == DBBackend.LEVELDB:
            from .leveldb_io import LevelDBReader
            return LevelDBReader(self.source_uri())
        return LmdbReader(self.source_uri())

    def image_dims(self) -> Tuple[int, int, int]:
        dims = getattr(self, "_dims", None)
        if dims is None:
            with self._reader() as r:
                for k, v in r.items(None, None):
                    d = Datum.from_binary(v)
                    dims = (int(d.channels), int(d.height),
                            int(d.width))
                    break
            if dims is None:
                raise ValueError(
                    f"{self.source_uri()!r}: empty database")
            self._dims = dims
        return dims


class SeqImageDataSource(DataSource):
    """SequenceFile of (id, Datum) records
    (source_class com.yahoo.ml.caffe.SeqImageDataSource)."""

    def records(self) -> Iterator[ImageRecord]:
        path = self.source_uri()
        files = sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if not f.startswith((".", "_"))) if os.path.isdir(path) \
            else [path]
        for i, f in enumerate(files):
            if i % self.num_ranks != self.rank and len(files) > 1:
                continue
            for key, val in SequenceFileReader(f):
                yield datum_to_record(key.encode("latin-1"), val)


class ImageDataFrame(DataSource):
    """Parquet DataFrame of images (source_class
    com.yahoo.ml.caffe.ImageDataFrame): optional columns id/label/
    channels/height/width/encoded + data (ImageDataFrame.scala:31-73)."""

    def records(self) -> Iterator[ImageRecord]:
        import pyarrow.parquet as pq
        c, h, w = self.image_dims()
        encoded_default = self.layer.memory_data_param.image_encoded
        table = pq.read_table(self.source_uri())
        cols = set(table.column_names)
        sel = list(self.layer.memory_data_param.dataframe_column_select)
        n = table.num_rows
        lo = self.rank * n // self.num_ranks
        hi = (self.rank + 1) * n // self.num_ranks
        tbl = table.slice(lo, hi - lo).to_pydict()
        for i in range(hi - lo):
            def col(name, default):
                return tbl[name][i] if name in cols else default
            data = col("data", b"") or b""
            if isinstance(data, list):
                data = bytes(data)
            yield (str(col("id", i)), float(col("label", 0.0) or 0.0),
                   int(col("channels", c)), int(col("height", h)),
                   int(col("width", w)),
                   bool(col("encoded", encoded_default)), data)


class ImageListSource(DataSource):
    """Caffe's ImageData layer (image_data_layer.cpp): a text list of
    `<path> <label>` lines, images loaded from disk (optionally under
    root_folder), resized to new_height x new_width.  rand_skip and
    shuffle follow the Caffe fields; rank striping shards the list."""

    def __init__(self, layer: LayerParameter, **kw):
        # Caffe's ImageData always resizes to new_height/new_width
        kw["resize"] = True
        super().__init__(layer, **kw)
        self._epoch = 0

    def _batch_size(self) -> int:
        return int(self.layer.image_data_param.batch_size)

    def source_uri(self) -> str:
        return _strip_scheme(self.layer.image_data_param.source)

    def image_dims(self) -> Tuple[int, int, int]:
        p = self.layer.image_data_param
        c = 3 if p.is_color else 1
        h, w = int(p.new_height), int(p.new_width)
        if not h or not w:
            cs = int(self.layer.transform_param.crop_size or 0)
            h = h or cs
            w = w or cs
        return c, h, w

    def _entries(self) -> List[Tuple[str, float]]:
        p = self.layer.image_data_param
        root = p.root_folder or ""
        out = []
        with open(self.source_uri()) as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                path, _, lbl = ln.rpartition(" ")
                if not path:      # no label column
                    path, lbl = lbl, "0"
                out.append((os.path.join(root, path), float(lbl)))
        return out

    def records(self) -> Iterator[ImageRecord]:
        """Caffe image_data_layer.cpp order: shuffle first (fresh
        permutation every epoch — ShuffleImages() on each wrap), then
        rand_skip once at startup only."""
        c, h, w = self.image_dims()
        p = self.layer.image_data_param
        entries = self._entries()
        epoch, self._epoch = self._epoch, self._epoch + 1
        if p.shuffle:
            # rank-INdependent seed: every rank must apply the same
            # permutation so the i % num_ranks striping below still
            # partitions the list disjointly
            seed = (self.seed + epoch * 131071) & 0x7FFFFFFF
            np.random.RandomState(seed).shuffle(entries)
        if int(p.rand_skip) and epoch == 0:
            skip = np.random.RandomState(self.seed).randint(
                0, int(p.rand_skip))
            entries = entries[skip:] + entries[:skip]
        for i, (path, lbl) in enumerate(entries):
            if i % self.num_ranks != self.rank:
                continue
            with open(path, "rb") as f:
                yield (os.path.basename(path), lbl, c, h, w, True,
                       f.read())


_CLASS_MAP = {
    "com.yahoo.ml.caffe.LMDB": LMDB,
    "com.yahoo.ml.caffe.SeqImageDataSource": SeqImageDataSource,
    "com.yahoo.ml.caffe.ImageDataFrame": ImageDataFrame,
    "LMDB": LMDB,
    "SeqImageDataSource": SeqImageDataSource,
    "ImageDataFrame": ImageDataFrame,
}


def get_source(layer: LayerParameter, **kw) -> DataSource:
    """Reflective factory keyed on prototxt `source_class`
    (DataSource.scala:130-167 analog)."""
    if layer.type == "HDF5Data":
        # Caffe layer type with no CoS source_class: route directly
        from .hdf5 import HDF5Source
        return HDF5Source(layer, **kw)
    if layer.type == "ImageData":
        return ImageListSource(layer, **kw)
    if layer.type == "Data" and not layer.source_class:
        # source_class-less Data layer: Caffe's own LMDB/LevelDB path;
        # WITH a source_class the CoS dispatch below takes precedence
        return CaffeDataSource(layer, **kw)
    cls_name = layer.source_class
    if not cls_name:
        raise ValueError(f"data layer {layer.name!r} has no source_class")
    if cls_name in _CLASS_MAP:
        return _CLASS_MAP[cls_name](layer, **kw)
    if cls_name == "com.yahoo.ml.caffe.DataFrameSource" \
            or cls_name.endswith("DataFrameSource"):
        from .dataframe import DataFrameSource
        return DataFrameSource(layer, **kw)
    if cls_name in ("StreamingDir", "com.yahoo.ml.caffe.StreamingDir"):
        # growing part-directory stream (continuous deployment,
        # data/streaming.py) — lazy import keeps the common sources
        # free of the deploy machinery
        from .streaming import StreamingDirSource
        return StreamingDirSource(layer, **kw)
    # user-provided "module:Class" extension point
    if ":" in cls_name:
        import importlib
        mod, cls = cls_name.rsplit(":", 1)
        return getattr(importlib.import_module(mod), cls)(layer, **kw)
    raise ValueError(f"unknown source_class {cls_name!r}")


def register_source(name: str, cls) -> None:
    _CLASS_MAP[name] = cls
