"""Data sources, transformers, and readers (LMDB, SequenceFile, Parquet)."""

from .lmdb_io import LmdbReader, LmdbWriter
from .queue_runner import (DROPPED, FeedQueue, PipelinedFeed,
                           TransformerPool, device_prefetch)
from .sequencefile import SequenceFileReader, SequenceFileWriter
from .source import (LMDB, DataSource, ImageDataFrame, SeqImageDataSource,
                     STOP_MARK, datum_to_record, get_source,
                     register_source)
# StreamingDirSource (data/streaming.py) is deliberately NOT
# re-exported here: get_source dispatches source_class "StreamingDir"
# lazily, keeping the common sources free of the deploy machinery —
# import caffeonspark_tpu.data.streaming directly where needed.
from .transformer import AugDraw, Transformer, load_mean_file
