"""Hadoop SequenceFile reader/writer.

The reference trains CaffeNet-ImageNet from SequenceFiles produced by
`tools/Binary2Sequence.scala:18-89` and read back via Spark's
`sc.sequenceFile` in `SeqImageDataSource.scala:35-64`.  This is a
dependency-free implementation of the same container: version-6 header,
Text/BytesWritable serialization, 16-byte sync markers every few KB.

Key class `org.apache.hadoop.io.Text` (VInt length + UTF-8), value class
`org.apache.hadoop.io.BytesWritable` (4-byte big-endian length + bytes).
Uncompressed/record-compressed records: {recordLen i32be, keyLen i32be,
key, value}; recordLen == -1 escapes a sync marker.  Record compression
compresses each value's serialized form; block compression groups records
into 4 compressed buffers (keyLengths/keys/valueLengths/values) per block,
each preceded by a VInt compressed size, block preceded by a sync escape
and a VInt record count.  Codecs: DefaultCodec (zlib), GzipCodec, Bzip2.
"""

from __future__ import annotations

import bz2
import gzip
import os
import struct
import zlib
from typing import Iterator, Tuple

SEQ_MAGIC = b"SEQ\x06"
TEXT_CLASS = "org.apache.hadoop.io.Text"
BYTES_CLASS = "org.apache.hadoop.io.BytesWritable"
DEFAULT_CODEC = "org.apache.hadoop.io.compress.DefaultCodec"
GZIP_CODEC = "org.apache.hadoop.io.compress.GzipCodec"
BZIP2_CODEC = "org.apache.hadoop.io.compress.BZip2Codec"
SYNC_INTERVAL = 2000  # bytes between sync markers (hadoop default ~2000)

_CODECS = {
    DEFAULT_CODEC: (zlib.compress, zlib.decompress),
    GZIP_CODEC: (gzip.compress, gzip.decompress),
    BZIP2_CODEC: (bz2.compress, bz2.decompress),
}


def _codec(name: str):
    if name not in _CODECS:
        raise NotImplementedError(f"SequenceFile codec {name!r}")
    return _CODECS[name]


def write_vint(v: int) -> bytes:
    if -112 <= v <= 127:
        return struct.pack("b", v)
    out = bytearray()
    neg = v < 0
    if neg:
        v = ~v
    length = (v.bit_length() + 7) // 8
    out.append((-121 if neg else -113) - (length - 1) & 0xFF)
    out.extend(v.to_bytes(length, "big"))
    return bytes(out)


def read_vint(buf: bytes, pos: int) -> Tuple[int, int]:
    (first,) = struct.unpack_from("b", buf, pos)
    pos += 1
    if first >= -112:
        return first, pos
    neg = first <= -121
    length = (-first - 120) if neg else (-first - 112)
    v = int.from_bytes(buf[pos:pos + length], "big")
    pos += length
    return (~v if neg else v), pos


def _write_text(s: str) -> bytes:
    b = s.encode("utf-8")
    return write_vint(len(b)) + b


def _read_text(buf: bytes, pos: int) -> Tuple[str, int]:
    n, pos = read_vint(buf, pos)
    return buf[pos:pos + n].decode("utf-8"), pos + n


class SequenceFileWriter:
    """(Text key, BytesWritable value) records.

    compression: None (default), "record" (each value's serialization
    compressed individually) or "block" (records buffered and flushed as
    4 compressed buffers per block, the hadoop BlockCompressWriter
    layout).
    """

    def __init__(self, path: str, *, key_class: str = TEXT_CLASS,
                 value_class: str = BYTES_CLASS,
                 compression: str | None = None,
                 codec: str = DEFAULT_CODEC,
                 block_size: int = 1 << 20,
                 sync_seed: int = 0x53455106):
        if compression not in (None, "record", "block"):
            raise ValueError(f"compression={compression!r}")
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._f = open(path, "wb")
        self.key_class = key_class
        self.value_class = value_class
        self.compression = compression
        self.codec = codec
        self._compress = _codec(codec)[0] if compression else None
        self._block_size = block_size
        import hashlib
        self.sync = hashlib.md5(
            f"cos-tpu-sync-{sync_seed}".encode()).digest()
        hdr = SEQ_MAGIC + _write_text(key_class) + _write_text(value_class)
        hdr += bytes([compression is not None, compression == "block"])
        if compression:
            hdr += _write_text(codec)
        hdr += struct.pack(">i", 0)   # metadata entries
        hdr += self.sync
        self._f.write(hdr)
        self._since_sync = 0
        # block-mode buffers: serialized key lengths / keys / value
        # lengths / values
        self._blk = ([], [], [], [])
        self._blk_bytes = 0

    def append(self, key: str, value: bytes) -> None:
        kb = _write_text(key)  # Text writable: VInt + utf8
        vb = struct.pack(">i", len(value)) + value  # BytesWritable
        if self.compression == "block":
            self._blk[0].append(write_vint(len(kb)))
            self._blk[1].append(kb)
            self._blk[2].append(write_vint(len(vb)))
            self._blk[3].append(vb)
            self._blk_bytes += len(kb) + len(vb)
            if self._blk_bytes >= self._block_size:
                self._flush_block()
            return
        if self.compression == "record":
            vb = self._compress(vb)
        rec = struct.pack(">ii", len(kb) + len(vb), len(kb))
        self._f.write(rec + kb + vb)
        self._since_sync += len(kb) + len(vb) + 8
        if self._since_sync >= SYNC_INTERVAL:
            self._f.write(struct.pack(">i", -1) + self.sync)
            self._since_sync = 0

    def _flush_block(self) -> None:
        n = len(self._blk[0])
        if n == 0:
            return
        out = [struct.pack(">i", -1), self.sync, write_vint(n)]
        for parts in self._blk:
            cb = self._compress(b"".join(parts))
            out.append(write_vint(len(cb)))
            out.append(cb)
        self._f.write(b"".join(out))
        self._blk = ([], [], [], [])
        self._blk_bytes = 0

    def close(self):
        if self.compression == "block":
            self._flush_block()
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


# exception classes a corrupt byte can surface as from the wire/codec
# internals — converted to ValueError at the reader boundaries.  The
# RECORD wrapper additionally catches OSError/EOFError (gzip's
# BadGzipFile and bz2 raise OSError subclasses; EOFError on truncated
# streams); the HEADER wrapper must NOT — it would relabel a genuine
# FileNotFoundError as corruption.
_WIRE_ERRORS = (struct.error, IndexError, OverflowError, zlib.error)
_DECOMPRESS_ERRORS = _WIRE_ERRORS + (OSError, EOFError)


class SequenceFileReader:
    def __init__(self, path: str):
        try:
            self._init(path)
        except _WIRE_ERRORS as e:
            raise ValueError(
                f"{path}: corrupt SequenceFile header: "
                f"{type(e).__name__}: {e}") from e

    def _init(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            self._buf = f.read()
        buf = self._buf
        if buf[:4] != SEQ_MAGIC:
            raise ValueError(f"{path}: not a SequenceFile (v6)")
        pos = 4
        self.key_class, pos = _read_text(buf, pos)
        self.value_class, pos = _read_text(buf, pos)
        compressed, block = buf[pos], buf[pos + 1]
        pos += 2
        self.compression = ("block" if block else
                            "record" if compressed else None)
        self.codec = None
        self._decompress = None
        if compressed or block:
            self.codec, pos = _read_text(buf, pos)
            self._decompress = _codec(self.codec)[1]
        (nmeta,) = struct.unpack_from(">i", buf, pos)
        pos += 4
        self.metadata = {}
        for _ in range(nmeta):
            k, pos = _read_text(buf, pos)
            v, pos = _read_text(buf, pos)
            self.metadata[k] = v
        self.sync = buf[pos:pos + 16]
        self._data_start = pos + 16

    def records(self) -> Iterator[Tuple[str, bytes]]:
        # malformed/truncated files surface as ValueError (the data
        # readers' one documented failure mode — matches LmdbReader and
        # proto.descriptor); a struct.error leak or a silently-dropped
        # truncated tail record would otherwise shorten epochs without
        # a trace
        try:
            if self.compression == "block":
                yield from self._block_records()
            else:
                yield from self._plain_records()
        except _DECOMPRESS_ERRORS as e:
            raise ValueError(
                f"{self.path}: corrupt SequenceFile: "
                f"{type(e).__name__}: {e}") from e

    def _plain_records(self) -> Iterator[Tuple[str, bytes]]:
        buf = self._buf
        pos = self._data_start
        n = len(buf)
        while pos < n:
            (rec_len,) = struct.unpack_from(">i", buf, pos)
            pos += 4
            if rec_len == -1:
                if buf[pos:pos + 16] != self.sync:
                    raise ValueError("sync marker mismatch (corrupt file)")
                pos += 16
                continue
            (key_len,) = struct.unpack_from(">i", buf, pos)
            pos += 4
            kend = pos + key_len
            if rec_len < key_len or key_len < 0 \
                    or pos + (rec_len - key_len) + key_len > n:
                raise ValueError(
                    f"{self.path}: truncated record at offset "
                    f"{pos - 8} (rec_len {rec_len}, key_len {key_len}, "
                    f"{n - pos} bytes left)")
            _, kpos = read_vint(buf, pos)
            key = buf[kpos:kend].decode("utf-8")   # UnicodeDecodeError
            #                       IS a ValueError — strict by design
            vsec = buf[kend:kend + (rec_len - key_len)]
            pos = kend + (rec_len - key_len)  # value section incl. length
            if self.compression == "record":
                vsec = self._decompress(bytes(vsec))
            (vlen,) = struct.unpack_from(">i", vsec, 0)
            if not 0 <= vlen <= len(vsec) - 4:
                raise ValueError(
                    f"{self.path}: corrupt BytesWritable length "
                    f"{vlen} (section {len(vsec) - 4} bytes)")
            yield key, bytes(vsec[4:4 + vlen])

    def _block_records(self) -> Iterator[Tuple[str, bytes]]:
        buf = self._buf
        pos = self._data_start
        n = len(buf)
        while pos < n:
            (esc,) = struct.unpack_from(">i", buf, pos)
            pos += 4
            if esc != -1 or buf[pos:pos + 16] != self.sync:
                raise ValueError("block boundary sync mismatch")
            pos += 16
            count, pos = read_vint(buf, pos)
            bufs = []
            for _ in range(4):  # keyLengths, keys, valueLengths, values
                clen, pos = read_vint(buf, pos)
                bufs.append(self._decompress(bytes(buf[pos:pos + clen])))
                pos += clen
            klens_b, keys_b, vlens_b, vals_b = bufs
            kp = vp = 0
            koff = voff = 0
            for _ in range(count):
                klen, kp = read_vint(klens_b, kp)
                vlen, vp = read_vint(vlens_b, vp)
                kser = keys_b[koff:koff + klen]
                koff += klen
                vser = vals_b[voff:voff + vlen]
                voff += vlen
                _, kdata = read_vint(kser, 0)
                (vraw,) = struct.unpack_from(">i", vser, 0)
                if not 0 <= vraw <= len(vser) - 4:
                    raise ValueError(
                        f"{self.path}: corrupt BytesWritable length "
                        f"{vraw} (section {len(vser) - 4} bytes)")
                yield (kser[kdata:].decode("utf-8"),
                       bytes(vser[4:4 + vraw]))

    def __iter__(self):
        return self.records()
