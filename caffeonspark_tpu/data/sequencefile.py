"""Hadoop SequenceFile reader/writer (uncompressed record format).

The reference trains CaffeNet-ImageNet from SequenceFiles produced by
`tools/Binary2Sequence.scala:18-89` and read back via Spark's
`sc.sequenceFile` in `SeqImageDataSource.scala:35-64`.  This is a
dependency-free implementation of the same container: version-6 header,
Text/BytesWritable serialization, 16-byte sync markers every few KB.

Key class `org.apache.hadoop.io.Text` (VInt length + UTF-8), value class
`org.apache.hadoop.io.BytesWritable` (4-byte big-endian length + bytes).
Records: {recordLen i32be, keyLen i32be, key, value}; recordLen == -1
escapes a sync marker.
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, Tuple

SEQ_MAGIC = b"SEQ\x06"
TEXT_CLASS = "org.apache.hadoop.io.Text"
BYTES_CLASS = "org.apache.hadoop.io.BytesWritable"
SYNC_INTERVAL = 2000  # bytes between sync markers (hadoop default ~2000)


def write_vint(v: int) -> bytes:
    if -112 <= v <= 127:
        return struct.pack("b", v)
    out = bytearray()
    neg = v < 0
    if neg:
        v = ~v
    length = (v.bit_length() + 7) // 8
    out.append((-121 if neg else -113) - (length - 1) & 0xFF)
    out.extend(v.to_bytes(length, "big"))
    return bytes(out)


def read_vint(buf: bytes, pos: int) -> Tuple[int, int]:
    (first,) = struct.unpack_from("b", buf, pos)
    pos += 1
    if first >= -112:
        return first, pos
    neg = first <= -121
    length = (-first - 120) if neg else (-first - 112)
    v = int.from_bytes(buf[pos:pos + length], "big")
    pos += length
    return (~v if neg else v), pos


def _write_text(s: str) -> bytes:
    b = s.encode("utf-8")
    return write_vint(len(b)) + b


def _read_text(buf: bytes, pos: int) -> Tuple[str, int]:
    n, pos = read_vint(buf, pos)
    return buf[pos:pos + n].decode("utf-8"), pos + n


class SequenceFileWriter:
    """(Text key, BytesWritable value) records, uncompressed."""

    def __init__(self, path: str, *, key_class: str = TEXT_CLASS,
                 value_class: str = BYTES_CLASS,
                 sync_seed: int = 0x53455106):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._f = open(path, "wb")
        self.key_class = key_class
        self.value_class = value_class
        import hashlib
        self.sync = hashlib.md5(
            f"cos-tpu-sync-{sync_seed}".encode()).digest()
        hdr = SEQ_MAGIC + _write_text(key_class) + _write_text(value_class)
        hdr += b"\x00\x00"            # compressed=false, block=false
        hdr += struct.pack(">i", 0)   # metadata entries
        hdr += self.sync
        self._f.write(hdr)
        self._since_sync = 0

    def append(self, key: str, value: bytes) -> None:
        kb = _write_text(key)  # Text writable: VInt + utf8
        rec = struct.pack(">ii", len(kb) + len(value) + 4, len(kb))
        # BytesWritable serializes as {len i32be, bytes}
        self._f.write(rec + kb + struct.pack(">i", len(value)) + value)
        self._since_sync += len(kb) + len(value) + 12
        if self._since_sync >= SYNC_INTERVAL:
            self._f.write(struct.pack(">i", -1) + self.sync)
            self._since_sync = 0

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class SequenceFileReader:
    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            self._buf = f.read()
        buf = self._buf
        if buf[:4] != SEQ_MAGIC:
            raise ValueError(f"{path}: not a SequenceFile (v6)")
        pos = 4
        self.key_class, pos = _read_text(buf, pos)
        self.value_class, pos = _read_text(buf, pos)
        compressed, block = buf[pos], buf[pos + 1]
        pos += 2
        if compressed or block:
            raise NotImplementedError("compressed SequenceFiles")
        (nmeta,) = struct.unpack_from(">i", buf, pos)
        pos += 4
        self.metadata = {}
        for _ in range(nmeta):
            k, pos = _read_text(buf, pos)
            v, pos = _read_text(buf, pos)
            self.metadata[k] = v
        self.sync = buf[pos:pos + 16]
        self._data_start = pos + 16

    def records(self) -> Iterator[Tuple[str, bytes]]:
        buf = self._buf
        pos = self._data_start
        n = len(buf)
        while pos < n:
            (rec_len,) = struct.unpack_from(">i", buf, pos)
            pos += 4
            if rec_len == -1:
                if buf[pos:pos + 16] != self.sync:
                    raise ValueError("sync marker mismatch (corrupt file)")
                pos += 16
                continue
            (key_len,) = struct.unpack_from(">i", buf, pos)
            pos += 4
            kend = pos + key_len
            _, kpos = read_vint(buf, pos)
            key = buf[kpos:kend].decode("utf-8")
            (vlen,) = struct.unpack_from(">i", buf, kend)
            value = buf[kend + 4:kend + 4 + vlen]
            pos = kend + (rec_len - key_len)  # value section incl. length
            yield key, bytes(value)

    def __iter__(self):
        return self.records()
