"""Minimal read-only LevelDB: the `data_param.backend: LEVELDB` path.

Caffe's Data layer reads either LMDB or LevelDB databases of serialized
`Datum` records (reference: caffe-public db_leveldb.cpp, reached from
CoS via `source_class`-less `Data` layers); the rebuild's LMDB side has
its own reader/writer (`lmdb_io.py`), and this module closes the
LevelDB half:

  * `LevelDBReader` — merges the database's LIVE SSTables and
    write-ahead logs into one sorted key→value stream, newest sequence
    number wins, deletions honored.  Live = the CURRENT→MANIFEST
    VersionEdit replay (new_file/deleted_file set + log_number floor);
    without a usable manifest it falls back to scanning every
    `*.ldb`/`*.sst`/`*.log` in the directory (fixture-grade databases).
    Tables are streamed block-by-block (one decompressed block per
    table in memory); only log entries are buffered (they are the
    recent, small tail of a database).
  * `LevelDBWriter` — enough of the on-disk format to build databases
    for tests/tools: sorted SSTables + a real CURRENT/MANIFEST
    (VersionEdit records in log framing).  It can emit blocks
    "snappy-compressed" as all-literal streams, which exercises the
    real decompression path on read.
  * pure-Python `snappy_decompress` (block format: varint length +
    literal/copy tags) — no native snappy library exists in this
    environment, and Caffe-written databases default to snappy.

Format notes (from the public LevelDB docs, table_format.md and
log_format.md):
  SSTable: [data blocks][meta][metaindex][index][footer(48B)]; each
  block = entries (shared_len, non_shared_len, value_len varints +
  key tail + value), restart array, then 1 trailer byte (0 = raw,
  1 = snappy) + crc32c(4).  Footer = metaindex handle + index handle
  (varint64 pairs) padded to 40 bytes + magic 0xdb4775248b80fb57.
  Index block values are handles of data blocks; keys are internal
  keys = user_key + 8 bytes (sequence<<8 | value_type).
  Log: 32 KiB blocks of records (crc32c(4), length(2), type(1) —
  FULL/FIRST/MIDDLE/LAST); payloads concatenate into WriteBatches:
  seq(8) count(4) then per entry type(1) + varint-framed key[/value].
"""

from __future__ import annotations

import glob
import heapq
import os
import struct
from typing import Dict, Iterator, List, Optional, Tuple

MAGIC = 0xDB4775248B80FB57
TYPE_DELETION = 0
TYPE_VALUE = 1

# log record types
LOG_FULL, LOG_FIRST, LOG_MIDDLE, LOG_LAST = 1, 2, 3, 4
LOG_BLOCK = 32768
LOG_HEADER = 7

_CRC_POLY = 0x82F63B78           # crc32c (Castagnoli)
_CRC_TABLE: List[int] = []
for _n in range(256):
    _c = _n
    for _ in range(8):
        _c = (_c >> 1) ^ _CRC_POLY if _c & 1 else _c >> 1
    _CRC_TABLE.append(_c)


def crc32c(data: bytes, crc: int = 0) -> int:
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def crc_mask(crc: int) -> int:
    """LevelDB stores masked crcs (log_format.md)."""
    return ((crc >> 15) | (crc << 17)) % (1 << 32) + 0xA282EAD8 & 0xFFFFFFFF


def _uvarint(buf: bytes, off: int) -> Tuple[int, int]:
    x = shift = 0
    while True:
        b = buf[off]
        off += 1
        x |= (b & 0x7F) << shift
        if not b & 0x80:
            return x, off
        shift += 7


def internal_key(key: bytes, seq: int = 1,
                 etype: int = TYPE_VALUE) -> bytes:
    """user key + 8-byte trailer (sequence << 8 | type) — the SSTable
    entry / manifest-boundary key encoding (table_format.md)."""
    return key + struct.pack("<Q", (seq << 8) | etype)


def _put_uvarint(x: int) -> bytes:
    out = bytearray()
    while x >= 0x80:
        out.append((x & 0x7F) | 0x80)
        x >>= 7
    out.append(x)
    return bytes(out)


def snappy_decompress(buf: bytes) -> bytes:
    """Snappy block format: uncompressed-length varint, then tagged
    elements (literal / copy with 1-, 2-, 4-byte offsets)."""
    n, off = _uvarint(buf, 0)
    out = bytearray()
    while off < len(buf):
        tag = buf[off]
        off += 1
        kind = tag & 3
        if kind == 0:                        # literal
            ln = (tag >> 2) + 1
            if ln > 60:                      # length in next 1-4 bytes
                nb = ln - 60
                ln = int.from_bytes(buf[off:off + nb], "little") + 1
                off += nb
            out += buf[off:off + ln]
            off += ln
            continue
        if kind == 1:                        # copy, 1-byte offset
            ln = ((tag >> 2) & 7) + 4
            o = ((tag >> 5) << 8) | buf[off]
            off += 1
        elif kind == 2:                      # copy, 2-byte offset
            ln = (tag >> 2) + 1
            o = int.from_bytes(buf[off:off + 2], "little")
            off += 2
        else:                                # copy, 4-byte offset
            ln = (tag >> 2) + 1
            o = int.from_bytes(buf[off:off + 4], "little")
            off += 4
        if o == 0 or o > len(out):
            raise ValueError("snappy: bad copy offset")
        for _ in range(ln):                  # may overlap itself
            out.append(out[-o])
    if len(out) != n:
        raise ValueError(f"snappy: length {len(out)} != header {n}")
    return bytes(out)


def _parse_block(raw: bytes) -> Iterator[Tuple[bytes, bytes]]:
    """Yield (key, value) from one decoded block (restart-prefix
    entries)."""
    if len(raw) < 4:
        return
    n_restarts = struct.unpack("<I", raw[-4:])[0]
    end = len(raw) - 4 - 4 * n_restarts
    off = 0
    key = b""
    while off < end:
        shared, off = _uvarint(raw, off)
        non_shared, off = _uvarint(raw, off)
        vlen, off = _uvarint(raw, off)
        key = key[:shared] + raw[off:off + non_shared]
        off += non_shared
        yield key, raw[off:off + vlen]
        off += vlen


class _Table:
    """One SSTable, streamed block by block via the index block."""

    def __init__(self, path: str, *, verify_crc: bool = True):
        self.path = path
        self.verify_crc = verify_crc
        self._f = open(path, "rb")
        self._size = os.path.getsize(path)
        if self._size < 48:
            raise ValueError(f"{path}: too small for an SSTable")
        self._f.seek(self._size - 48)
        footer = self._f.read(48)
        if struct.unpack("<Q", footer[40:])[0] != MAGIC:
            raise ValueError(f"{path}: bad SSTable magic")
        _, off = _uvarint(footer, 0)         # metaindex handle offset
        _, off = _uvarint(footer, off)       # metaindex handle size
        idx_off, off = _uvarint(footer, off)
        idx_size, off = _uvarint(footer, off)
        self._index = list(_parse_block(self._read_block(idx_off,
                                                         idx_size)))

    def _read_block(self, off: int, size: int) -> bytes:
        self._f.seek(off)
        raw = self._f.read(size + 5)         # + type byte + crc32c
        block, ctype, crc = raw[:size], raw[size], raw[size + 1:size + 5]
        if self.verify_crc:
            want = struct.unpack("<I", crc)[0]
            if crc_mask(crc32c(raw[:size + 1])) != want:
                raise ValueError(f"{self.path}: block crc mismatch "
                                 f"@{off}")
        if ctype == 1:
            block = snappy_decompress(block)
        elif ctype != 0:
            raise ValueError(f"{self.path}: unknown block compression "
                             f"{ctype}")
        return block

    def entries(self, lo: Optional[bytes] = None
                ) -> Iterator[Tuple[bytes, int, int, bytes]]:
        """Yield (user_key, seq, type, value) in key order, starting at
        the first block that can contain `lo` (index keys are >= the
        block's last key, so earlier blocks are skipped undecoded)."""
        for idx_key, handle in self._index:
            if lo is not None and len(idx_key) >= 8 \
                    and idx_key[:-8] < lo:
                continue
            boff, p = _uvarint(handle, 0)
            bsize, _ = _uvarint(handle, p)
            for ikey, val in _parse_block(self._read_block(boff, bsize)):
                if len(ikey) < 8:
                    continue
                tag = struct.unpack("<Q", ikey[-8:])[0]
                yield ikey[:-8], tag >> 8, tag & 0xFF, val

    def close(self):
        self._f.close()


def _log_records(path: str, *, verify_crc: bool = True
                 ) -> List[bytes]:
    """Reassembled record payloads from a LevelDB log-format file
    (32 KiB blocks, FULL/FIRST/MIDDLE/LAST fragments).  Both the WAL
    (WriteBatch payloads) and the MANIFEST (VersionEdit payloads) use
    this framing."""
    with open(path, "rb") as f:
        data = f.read()
    payload = bytearray()
    off = 0
    records: List[bytes] = []
    while off + LOG_HEADER <= len(data):
        block_left = LOG_BLOCK - off % LOG_BLOCK
        if block_left < LOG_HEADER:          # trailer padding
            off += block_left
            continue
        crc, length, rtype = struct.unpack("<IHB",
                                           data[off:off + LOG_HEADER])
        off += LOG_HEADER
        if rtype == 0 and length == 0 and crc == 0:
            break                            # zero padding = EOF
        frag = data[off:off + length]
        off += length
        if verify_crc and crc != crc_mask(
                crc32c(frag, crc32c(bytes([rtype])))):
            raise ValueError(f"{path}: log record crc mismatch")
        if rtype in (LOG_FULL, LOG_FIRST):
            payload = bytearray(frag)
        else:
            payload += frag
        if rtype in (LOG_FULL, LOG_LAST):
            records.append(bytes(payload))
    return records


def _log_entries(path: str, *, verify_crc: bool = True
                 ) -> Iterator[Tuple[bytes, int, int, bytes]]:
    """(user_key, seq, type, value) from a write-ahead log file."""
    batches = _log_records(path, verify_crc=verify_crc)
    for batch in batches:
        if len(batch) < 12:
            continue
        seq = struct.unpack("<Q", batch[:8])[0]
        count = struct.unpack("<I", batch[8:12])[0]
        p = 12
        for i in range(count):
            etype = batch[p]
            p += 1
            klen, p = _uvarint(batch, p)
            key = batch[p:p + klen]
            p += klen
            if etype == TYPE_VALUE:
                vlen, p = _uvarint(batch, p)
                val = batch[p:p + vlen]
                p += vlen
            else:
                val = b""
            yield key, seq + i, etype, val


# VersionEdit tags (leveldb version_edit.cc)
_VE_COMPARATOR = 1
_VE_LOG_NUMBER = 2
_VE_NEXT_FILE = 3
_VE_LAST_SEQ = 4
_VE_COMPACT_POINTER = 5
_VE_DELETED_FILE = 6
_VE_NEW_FILE = 7
_VE_PREV_LOG = 9


def _live_file_set(path: str, *, verify_crc: bool = True
                   ) -> Optional[Tuple[set, int, int]]:
    """Replay CURRENT -> MANIFEST VersionEdits into (live-SSTable
    file-number set, log_number, prev_log_number).  Live WALs are those
    numbered >= log_number OR == prev_log_number — LevelDB's own
    recovery rule; anything else is obsolete (a min() floor would
    replay logs strictly between prev_log and log_number and resurrect
    deleted keys).  Returns None when the database has no usable
    manifest (absent, stub, or unparseable) — callers then fall back to
    scanning every file, the pre-round-4 behavior, which is fine for
    fixtures but can resurrect deleted keys from crash-leftover
    obsolete tables in real Caffe-written databases."""
    try:
        with open(os.path.join(path, "CURRENT"), "r") as f:
            name = f.read().strip()
    except OSError:
        return None
    man = os.path.join(path, name)
    if not os.path.isfile(man) or os.path.getsize(man) == 0:
        return None
    live: set = set()
    log_floor = 0
    prev_log = 0

    def _skip_string(payload, p):
        ln, p = _uvarint(payload, p)
        return p + ln

    try:
        for payload in _log_records(man, verify_crc=verify_crc):
            p = 0
            while p < len(payload):
                tag, p = _uvarint(payload, p)
                if tag == _VE_COMPARATOR:
                    p = _skip_string(payload, p)
                elif tag == _VE_LOG_NUMBER:
                    log_floor, p = _uvarint(payload, p)
                elif tag in (_VE_NEXT_FILE, _VE_LAST_SEQ):
                    _, p = _uvarint(payload, p)
                elif tag == _VE_COMPACT_POINTER:
                    _, p = _uvarint(payload, p)          # level
                    p = _skip_string(payload, p)         # internal key
                elif tag == _VE_DELETED_FILE:
                    _, p = _uvarint(payload, p)          # level
                    fn, p = _uvarint(payload, p)
                    live.discard(fn)
                elif tag == _VE_NEW_FILE:
                    _, p = _uvarint(payload, p)          # level
                    fn, p = _uvarint(payload, p)
                    _, p = _uvarint(payload, p)          # file size
                    p = _skip_string(payload, p)         # smallest
                    p = _skip_string(payload, p)         # largest
                    live.add(fn)
                elif tag == _VE_PREV_LOG:
                    prev_log, p = _uvarint(payload, p)
                else:
                    raise ValueError(
                        f"{man}: unknown VersionEdit tag {tag}")
            if p != len(payload):
                raise ValueError(f"{man}: trailing VersionEdit bytes")
    except (ValueError, IndexError):
        return None
    return live, log_floor, prev_log


def _file_number(p: str) -> Optional[int]:
    stem = os.path.basename(p).split(".", 1)[0]
    return int(stem) if stem.isdigit() else None


class LevelDBReader:
    """Directory of SSTables + logs → one sorted (key, value) stream.

    API mirrors `LmdbReader`: context manager, `items(lo, hi)`,
    `partition_ranges(n)` — so `CaffeDataSource` treats both backends
    uniformly."""

    def __init__(self, path: str, *, verify_crc: bool = True):
        self.path = path
        if not os.path.isdir(path):
            raise FileNotFoundError(
                f"LevelDB directory not found: {path!r}")
        table_paths = sorted(glob.glob(os.path.join(path, "*.ldb"))
                             + glob.glob(os.path.join(path, "*.sst")))
        log_paths = sorted(glob.glob(os.path.join(path, "*.log")))
        if not table_paths and not log_paths:
            raise ValueError(
                f"{path!r} has no *.ldb/*.sst/*.log files — not a "
                "LevelDB database")
        # honor the MANIFEST's live-file set when one exists: a
        # crash-leftover obsolete table whose deletion marker was
        # compacted away would otherwise resurrect deleted keys
        live = _live_file_set(path, verify_crc=verify_crc)
        if live is not None:
            live_nums, log_num, prev_log = live
            table_paths = [p for p in table_paths
                           if _file_number(p) in live_nums]
            log_paths = [p for p in log_paths
                         if (_file_number(p) or 0) >= log_num
                         or _file_number(p) == prev_log]
        self._tables = [_Table(p, verify_crc=verify_crc)
                        for p in table_paths]
        self._logs = log_paths
        self._verify_crc = verify_crc

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self):
        for t in self._tables:
            t.close()

    def _merged(self, lo: Optional[bytes] = None
                ) -> Iterator[Tuple[bytes, bytes]]:
        streams = [t.entries(lo) for t in self._tables]
        log_items: List[Tuple[bytes, int, int, bytes]] = []
        for lp in self._logs:
            log_items.extend(_log_entries(lp,
                                          verify_crc=self._verify_crc))
        log_items.sort(key=lambda e: (e[0], -e[1]))
        streams.append(iter(log_items))
        # highest sequence first within a user key: newest version wins
        merged = heapq.merge(*streams,
                             key=lambda e: (e[0], -e[1]))
        prev: Optional[bytes] = None
        for key, seq, etype, val in merged:
            if key == prev:
                continue                     # older version, shadowed
            prev = key
            if etype == TYPE_VALUE:
                yield key, val

    def items(self, lo: Optional[bytes] = None,
              hi: Optional[bytes] = None
              ) -> Iterator[Tuple[bytes, bytes]]:
        for k, v in self._merged(lo=lo):
            if lo is not None and k < lo:
                continue
            if hi is not None and k >= hi:
                break
            yield k, v

    def keys(self) -> List[bytes]:
        return [k for k, _ in self._merged()]

    def partition_ranges(self, num_partitions: int
                         ) -> List[Tuple[Optional[bytes],
                                         Optional[bytes]]]:
        """Exactly num_partitions contiguous key ranges (the
        LmdbRDD.scala:41-95 key-scan partitioning idea).  Like
        LmdbReader, a surplus rank gets a DISTINCT empty (k, k) range —
        never an alias of another rank's keys.  Bounds come from the
        SSTable index blocks when they are fine-grained enough (no data
        decode), else from a full key scan."""
        n = num_partitions
        if n <= 1:
            return [(None, None)]
        ks = self._index_keys()
        if len(ks) >= 4 * n:
            count, key_at = len(ks), ks      # list indexes like the dict
        else:
            count, key_at = self._stream_boundaries(n)
        bounds: List[Tuple[Optional[bytes], Optional[bytes]]] = []
        for i in range(n):
            si = count * i // n
            ei = count * (i + 1) // n
            if si >= ei:
                k0 = key_at[0] if count else b""
                bounds.append((k0, k0))
                continue
            lo = None if i == 0 else key_at[si]
            hi = None if ei >= count else key_at[ei]
            bounds.append((lo, hi))
        return bounds

    def _stream_boundaries(self, n: int
                           ) -> Tuple[int, Dict[int, bytes]]:
        """Boundary keys for n partitions from two streaming scans —
        O(n) memory, never a materialized full key list (real
        Caffe-written databases hold millions of keys)."""
        count = sum(1 for _ in self._merged())
        needed = {0} | {count * i // n for i in range(1, n)}
        key_at: Dict[int, bytes] = {}
        for idx, (k, _) in enumerate(self._merged()):
            if idx in needed:
                key_at[idx] = k
                if len(key_at) == len(needed):
                    break
        return count, key_at

    def _index_keys(self) -> List[bytes]:
        """Sorted user keys from the tables' index blocks — block-level
        granularity, no data-block decompression."""
        ks = set()
        for t in self._tables:
            for ikey, _ in t._index:
                if len(ikey) >= 8:
                    ks.add(ikey[:-8])
        return sorted(ks)


class LevelDBWriter:
    """Write a sorted single-SSTable LevelDB (enough for tests and the
    `cos_tools leveldb2lmdb`/fixture tooling; real Caffe databases are
    far bigger but structurally identical).  `snappy=True` stores
    blocks as all-literal snappy streams (valid per the format, and
    exercises read-side decompression)."""

    def __init__(self, path: str, *, block_size: int = 16384,
                 snappy: bool = False):
        self.path = path
        self.block_size = block_size
        self.snappy = snappy

    @staticmethod
    def _block(entries: List[Tuple[bytes, bytes]]) -> bytes:
        out = bytearray()
        prev = b""
        restarts = [0]
        for i, (k, v) in enumerate(entries):
            if i % 16 == 0:
                if i:
                    restarts.append(len(out))
                shared = 0
            else:
                shared = 0
                while (shared < len(prev) and shared < len(k)
                       and prev[shared] == k[shared]):
                    shared += 1
            out += _put_uvarint(shared) + _put_uvarint(len(k) - shared)
            out += _put_uvarint(len(v)) + k[shared:] + v
            prev = k
        for r in restarts:
            out += struct.pack("<I", r)
        out += struct.pack("<I", len(restarts))
        return bytes(out)

    @staticmethod
    def _snappy_literal(data: bytes) -> bytes:
        """Valid snappy stream using only literal elements."""
        out = bytearray(_put_uvarint(len(data)))
        off = 0
        while off < len(data):
            chunk = data[off:off + 65536]
            ln = len(chunk) - 1
            if ln < 60:
                out.append(ln << 2)
            else:
                out.append(61 << 2)          # 61 = 2-byte length literal
                out += struct.pack("<H", ln)
            out += chunk
            off += len(chunk)
        return bytes(out)

    def write(self, records: List[Tuple[bytes, bytes]], *,
              file_number: int = 5) -> None:
        self.write_table(records, file_number=file_number)
        records = sorted(records)
        files = []
        if records:
            size = os.path.getsize(os.path.join(
                self.path, f"{file_number:06d}.ldb"))
            files.append((file_number, size,
                          internal_key(records[0][0]),
                          internal_key(records[-1][0])))
        self.write_manifest(files, log_number=0)

    def write_table(self, records: List[Tuple[bytes, bytes]], *,
                    file_number: int = 5) -> None:
        """One sorted SSTable, no CURRENT/MANIFEST bookkeeping — tests
        use this to plant crash-leftover obsolete tables."""
        os.makedirs(self.path, exist_ok=True)
        records = sorted(records)
        with open(os.path.join(self.path,
                               f"{file_number:06d}.ldb"), "wb") as f:
            index: List[Tuple[bytes, bytes]] = []

            def emit(block_entries):
                raw = self._block(block_entries)
                if self.snappy:
                    payload, ctype = self._snappy_literal(raw), 1
                else:
                    payload, ctype = raw, 0
                off = f.tell()
                crc = crc_mask(crc32c(payload + bytes([ctype])))
                f.write(payload + bytes([ctype])
                        + struct.pack("<I", crc))
                handle = _put_uvarint(off) + _put_uvarint(len(payload))
                # index key: any key >= last key in block works; use it
                index.append((block_entries[-1][0], handle))

            cur: List[Tuple[bytes, bytes]] = []
            size = 0
            for k, v in records:
                ikey = internal_key(k)
                cur.append((ikey, v))
                size += len(ikey) + len(v)
                if size >= self.block_size:
                    emit(cur)
                    cur, size = [], 0
            if cur:
                emit(cur)
            # metaindex (empty block) + index + footer
            meta_raw = self._block([])
            meta_off = f.tell()
            crc = crc_mask(crc32c(meta_raw + b"\x00"))
            f.write(meta_raw + b"\x00" + struct.pack("<I", crc))
            meta_handle = (_put_uvarint(meta_off)
                           + _put_uvarint(len(meta_raw)))
            idx_raw = self._block(index)
            idx_off = f.tell()
            crc = crc_mask(crc32c(idx_raw + b"\x00"))
            f.write(idx_raw + b"\x00" + struct.pack("<I", crc))
            idx_handle = (_put_uvarint(idx_off)
                          + _put_uvarint(len(idx_raw)))
            footer = meta_handle + idx_handle
            footer += b"\x00" * (40 - len(footer))
            footer += struct.pack("<Q", MAGIC)
            f.write(footer)

    def write_manifest(self, files: List[Tuple[int, int, bytes, bytes]],
                       *, log_number: int = 0,
                       manifest_number: int = 4) -> None:
        """Real CURRENT + MANIFEST: one VersionEdit record declaring
        comparator, live log floor, and the live table set as
        (file_number, size, smallest_ikey, largest_ikey) level-0
        entries — the read side replays this in `_live_file_set`."""
        os.makedirs(self.path, exist_ok=True)
        cmp_name = b"leveldb.BytewiseComparator"
        edit = bytearray()
        edit += _put_uvarint(_VE_COMPARATOR)
        edit += _put_uvarint(len(cmp_name)) + cmp_name
        edit += _put_uvarint(_VE_LOG_NUMBER) + _put_uvarint(log_number)
        for num, size, smallest, largest in files:
            edit += _put_uvarint(_VE_NEW_FILE) + _put_uvarint(0)
            edit += _put_uvarint(num) + _put_uvarint(size)
            edit += _put_uvarint(len(smallest)) + smallest
            edit += _put_uvarint(len(largest)) + largest
        name = f"MANIFEST-{manifest_number:06d}"
        with open(os.path.join(self.path, name), "wb") as f:
            self._append_framed(f, bytes(edit))
        with open(os.path.join(self.path, "CURRENT"), "w") as f:
            f.write(name + "\n")

    @staticmethod
    def _append_framed(f, payload: bytes) -> None:
        """Write one record in log framing (32 KiB blocks, fragment
        types) — shared by the WAL and the MANIFEST."""
        off = 0
        first = True
        while first or off < len(payload):
            room = LOG_BLOCK - f.tell() % LOG_BLOCK - LOG_HEADER
            frag = payload[off:off + room]
            off += len(frag)
            end = off >= len(payload)
            rtype = (LOG_FULL if first and end else
                     LOG_FIRST if first else
                     LOG_LAST if end else LOG_MIDDLE)
            crc = crc_mask(crc32c(frag, crc32c(bytes([rtype]))))
            f.write(struct.pack("<IHB", crc, len(frag), rtype) + frag)
            first = False

    def write_log(self, records: List[Tuple[bytes, bytes]],
                  seq_start: int = 100, *,
                  file_number: int = 7) -> None:
        """Append records as a write-ahead log file (the un-compacted
        recent-writes path)."""
        batch = bytearray(struct.pack("<QI", seq_start, len(records)))
        for k, v in records:
            batch += bytes([TYPE_VALUE]) + _put_uvarint(len(k)) + k
            batch += _put_uvarint(len(v)) + v
        os.makedirs(self.path, exist_ok=True)
        with open(os.path.join(self.path,
                               f"{file_number:06d}.log"), "wb") as f:
            self._append_framed(f, bytes(batch))
