"""Bounded-queue feed runtime: the QueuePair / backpressure protocol of
the reference executor, re-expressed for a host→TPU pipeline.

Reference semantics preserved (SURVEY §2.2):
  * bounded source queue, capacity 1024 (`DataSource.scala:67-76`);
  * STOP_MARK sentinel ends an epoch (`CaffeProcessor.scala:205`);
  * `feedQueue` spins `offer` until the solver completes — device→task
    backpressure (`CaffeProcessor.scala:192-198`);
  * transformer threads decode/augment while the device computes
    (`transform_thread_per_device`, `CaffeProcessor.scala:54-55`) —
    here `TransformerPool`, an ORDERED multi-threaded pack pool;
  * double-buffered transformer→solver handoff (QueuePair depth 2,
    `CaffeProcessor.scala:32-35`) — here `device_prefetch`, optionally
    with a background stager thread so the H2D transfer and the jitted
    device-transform dispatch also leave the solver thread.
"""

from __future__ import annotations

import collections
import logging
import os
import queue
import threading
import time
from typing import Callable, Dict, Iterator, Optional

import jax
import numpy as np

from .source import STOP_MARK

_LOG = logging.getLogger(__name__)

SOURCE_QUEUE_CAPACITY = 1024

# consecutive pack failures that abort the pipeline (systematic
# data/config error) — one constant for both the standalone pool's
# default policy and CaffeProcessor.MAX_CONSECUTIVE_DROPS
DROP_LIMIT_DEFAULT = 20

# ordered-slot marker for a batch the pool dropped after a pack error
# (corrupt record): the slot still advances the sequence so validation
# rounds can count it, train consumers skip it
DROPPED = object()

_END = object()          # worker/stager shutdown sentinel


def transform_threads(default: int = 2) -> int:
    """Transformer-pool width per processor (COS_TRANSFORM_THREADS;
    0 = inline legacy path: pack on the solver thread)."""
    try:
        return max(0, int(os.environ.get("COS_TRANSFORM_THREADS",
                                         str(default))))
    except ValueError:
        return default


def steps_per_loop(default: int = 1) -> int:
    """Fused multi-step chunk size K (COS_STEPS_PER_LOOP; 1 = legacy
    per-step dispatch).  K solver iterations compile into one XLA
    program (Solver.build_train_step_many) fed by a stacked (K, batch…)
    block, amortizing the host→device dispatch round-trip — the
    SparkNet/FireCaffe iterations-per-loop lever."""
    try:
        return max(1, int(os.environ.get("COS_STEPS_PER_LOOP",
                                         str(default))))
    except ValueError:
        return default


def stage_depth(default: int = 2) -> int:
    """Background-stager handoff depth (COS_STAGE_DEPTH)."""
    try:
        return max(1, int(os.environ.get("COS_STAGE_DEPTH",
                                         str(default))))
    except ValueError:
        return default


def stage_background(default: Optional[bool] = None) -> bool:
    """Run the device stager on its own thread?  Default: only on
    accelerator backends, where H2D rides a DMA engine and host cores
    are free to run the stager.  On the CPU backend every device op
    (device_put included) funnels through jax's single async dispatch
    executor, so a stager thread adds scheduler/handoff latency without
    adding bandwidth — staging stays on the consumer thread there.
    COS_STAGE_BG=0/1 overrides."""
    env = os.environ.get("COS_STAGE_BG")
    if env is not None:
        return env.lower() not in ("0", "", "false", "no")
    if default is not None:
        return default
    return jax.default_backend() != "cpu"


def tune_decode_threads(src, pool_width: int):
    """Under a multi-worker transformer pool, inter-batch parallelism
    replaces the native decoder's intra-batch thread pool: N workers
    each spawning the decoder's default ncores threads oversubscribes
    the host (measured 2.6x slower packs on a 2-core box).  Pin
    per-call decode to one thread unless the caller set num_threads
    explicitly."""
    if pool_width > 1 and getattr(src, "num_threads", None) == 0:
        src.num_threads = 1


class FeedQueue:
    """Bounded record queue with STOP_MARK epoch protocol."""

    def __init__(self, capacity: int = SOURCE_QUEUE_CAPACITY):
        self._q: queue.Queue = queue.Queue(maxsize=capacity)
        self._stopped = False

    def offer(self, item, timeout: Optional[float] = None) -> bool:
        """Put with backpressure; returns False if stopped or the
        deadline expires.  timeout=None blocks until space (polling in
        short slices so stop() stays responsive); a numeric timeout is
        a real deadline for the WHOLE call — including timeout=0, a
        single non-blocking attempt."""
        if self._stopped:
            return False
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            if self._stopped:
                return False
            if deadline is None:
                wait = 0.1
            else:
                wait = deadline - time.monotonic()
                if wait <= 0:
                    try:
                        self._q.put_nowait(item)
                        return True
                    except queue.Full:
                        return False
                wait = min(0.1, wait)
            try:
                self._q.put(item, timeout=wait)
                return True
            except queue.Full:
                continue

    def reset(self):
        """Re-arm a stopped queue (processor restart) and drop leftovers."""
        self._stopped = False
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                return

    def mark_epoch_end(self):
        self.offer(STOP_MARK)

    def take(self, timeout: Optional[float] = None):
        """Blocking get; a numeric timeout (INCLUDING 0) raises
        queue.Empty on expiry instead of falling into the forever-
        blocking branch."""
        if timeout is None:
            return self._q.get()
        return self._q.get(timeout=timeout)

    def stop(self):
        self._stopped = True
        try:                     # wake a consumer blocked in take()
            self._q.put_nowait(STOP_MARK)
        except queue.Full:
            pass

    @property
    def stopped(self) -> bool:
        return self._stopped

    def __len__(self):
        return self._q.qsize()


class TransformerPool:
    """Ordered multi-threaded decode/augment/pack pool — the
    transform_thread_per_device analog (`CaffeProcessor.scala:54-55`)
    that takes host transform work off the solver thread.

    One dispatcher thread drains `feed`, groups records into
    batch-sized buffers (STOP_MARK drops the ragged epoch tail, a
    `None` record terminates the pool), pre-draws the per-batch
    augmentation via `draw_fn` IN FEED ORDER (so on clean data the
    pool reproduces the inline path's RNG stream exactly), and hands
    (seq, buffer, draw) to `num_threads` workers calling
    `pack(buffer, draw)`.  Output is re-sequenced: `take()`/iteration
    yields batches in feed order regardless of worker scheduling, with
    exactly one terminal condition per pool.  The pre-draw happens at
    dispatch, so a batch whose pack later FAILS has still consumed the
    RNG — on dirty data the pooled stream diverges from the inline
    path after the first drop (deliberate: drawing after decode would
    serialize the workers, and the reference's per-thread transformer
    RNGs never had cross-path parity at all).

    Pack failures follow the reference's per-iteration tolerance: the
    slot becomes DROPPED (skipped by train consumers, countable by
    validation), drop accounting is thread-safe, and `drop_limit`
    consecutive failures abort the pipeline (the error re-raises from
    `take()`).  `on_pack_ok`/`on_pack_error` externalize the counters
    (CaffeProcessor shares one counter across train + validation);
    an `on_pack_error` that raises aborts the pool the same way.
    """

    def __init__(self, feed: FeedQueue, batch_size: int,
                 pack: Callable, *, num_threads: int = 2,
                 draw_fn: Optional[Callable] = None,
                 on_pack_ok: Optional[Callable] = None,
                 on_pack_error: Optional[Callable] = None,
                 drop_limit: int = DROP_LIMIT_DEFAULT,
                 depth: Optional[int] = None,
                 metrics=None,
                 should_stop: Optional[Callable[[], bool]] = None):
        self.feed = feed
        self.batch_size = int(batch_size)
        self.pack = pack
        self.num_threads = max(1, int(num_threads))
        self.draw_fn = draw_fn
        self.on_pack_ok = on_pack_ok
        self.on_pack_error = on_pack_error
        self.drop_limit = drop_limit
        self.depth = depth if depth is not None else 2 * self.num_threads
        self.metrics = metrics
        self._ext_stop = should_stop or (lambda: False)
        self._stopped = False
        self._work: queue.Queue = queue.Queue(maxsize=max(1, self.depth))
        # results window: bounded by construction (a worker blocks
        # depositing seq >= next_emit + window), so a stalled consumer
        # backpressures the whole pool instead of growing the dict
        self._window = self.depth + self.num_threads
        self._cond = threading.Condition()
        self._results: Dict[int, object] = {}
        self._next_emit = 0
        self._in_seq: Optional[int] = None   # total batches dispatched
        self._error: Optional[BaseException] = None
        self._consecutive = 0
        self.drops = 0
        self._threads: list = []
        self._started = False

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "TransformerPool":
        assert not self._started, "pool already started"
        self._started = True
        d = threading.Thread(target=self._dispatch, daemon=True,
                             name="cos-xform-dispatch")
        self._threads.append(d)
        for i in range(self.num_threads):
            t = threading.Thread(target=self._worker, daemon=True,
                                 name=f"cos-xform-{i}")
            self._threads.append(t)
        for t in self._threads:
            t.start()
        return self

    def stop(self, join_timeout: Optional[float] = None):
        """Flag every pool thread down; optionally reap them."""
        self._stopped = True
        with self._cond:
            self._cond.notify_all()
        if join_timeout is not None:
            self.join(timeout=join_timeout)

    def join(self, timeout: Optional[float] = None):
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        for t in self._threads:
            t.join(timeout=None if deadline is None
                   else max(0.0, deadline - time.monotonic()))

    def _should_stop(self) -> bool:
        # an abort (_error) halts the whole pipeline too: without it
        # the dispatcher would keep draining records and workers would
        # keep decoding doomed batches until the consumer reaches its
        # teardown
        return (self._stopped or self._error is not None
                or self._ext_stop())

    def _fail(self, exc: BaseException):
        with self._cond:
            if self._error is None:
                self._error = exc
            self._cond.notify_all()

    # -- dispatcher: feed order, epoch boundaries, ordered draws --------
    def _dispatch(self):
        buf: list = []
        seq = 0
        try:
            while not self._should_stop():
                try:
                    item = self.feed.take(timeout=0.2)
                except queue.Empty:
                    if self.feed.stopped:
                        break
                    continue
                if item is None:
                    break               # terminal sentinel
                if item is STOP_MARK:
                    # epoch boundary: drop the ragged tail
                    if buf and self.metrics is not None:
                        self.metrics.incr("ragged_tail_records",
                                          len(buf))
                    buf = []
                    if self.feed.stopped:
                        break           # stop()-wake, not an epoch
                    continue
                buf.append(item)
                if len(buf) == self.batch_size:
                    draw = (self.draw_fn(len(buf))
                            if self.draw_fn is not None else None)
                    if not self._put_work((seq, buf, draw)):
                        return
                    seq += 1
                    buf = []
        except BaseException as e:      # noqa: BLE001 — surfaced on take()
            self._fail(e)
        finally:
            with self._cond:
                self._in_seq = seq
                self._cond.notify_all()
            for _ in range(self.num_threads):
                self._put_work(_END, force=True)

    def _put_work(self, item, force: bool = False) -> bool:
        while True:
            if not force and self._should_stop():
                return False
            try:
                self._work.put(item, timeout=0.2)
                return True
            except queue.Full:
                if force and self._should_stop():
                    # workers are exiting on their own stop checks;
                    # don't spin on a full queue forever
                    return False
                continue

    # -- workers: pack + thread-safe drop accounting --------------------
    def _record_ok(self):
        cb = self.on_pack_ok
        if cb is not None:
            cb()
            return
        with self._cond:
            self._consecutive = 0

    def _record_drop(self, exc: Exception):
        with self._cond:
            self.drops += 1
        cb = self.on_pack_error
        if cb is not None:
            cb(exc)                     # may raise to abort the pool
            return
        if self.metrics is not None:
            self.metrics.incr("dropped_batches")
        _LOG.warning("dropping batch after record error: %s", exc)
        with self._cond:
            self._consecutive += 1
            n = self._consecutive
        if n >= self.drop_limit:
            raise RuntimeError(
                f"{n} consecutive batch failures — systematic "
                f"data/config error; last: {exc}") from exc

    def _worker(self):
        while True:
            try:
                item = self._work.get(timeout=0.2)
            except queue.Empty:
                if self._should_stop():
                    return
                continue
            if item is _END:
                return
            seq, buf, draw = item
            t0 = time.perf_counter()
            try:
                batch = self.pack(buf, draw)
            except Exception as e:      # pack failure → DROPPED slot
                batch = DROPPED
                try:
                    self._record_drop(e)
                except BaseException as abort:  # noqa: BLE001
                    self._fail(abort)
            else:
                if self.metrics is not None:
                    self.metrics.add("pack", time.perf_counter() - t0)
                try:
                    self._record_ok()
                except BaseException as abort:  # noqa: BLE001
                    self._fail(abort)
            self._deposit(seq, batch)

    def _deposit(self, seq: int, batch):
        with self._cond:
            while (self._error is None and not self._should_stop()
                   and seq - self._next_emit >= self._window):
                self._cond.wait(0.2)
            self._results[seq] = batch
            self._cond.notify_all()

    # -- consumer -------------------------------------------------------
    def take(self, timeout: Optional[float] = None, *,
             skip_dropped: bool = True):
        """Next packed batch in feed order.  Raises queue.Empty when
        `timeout` expires, re-raises a pipeline abort, returns None
        when the input is exhausted or the pool is stopping.  With
        skip_dropped=False a pack-failed slot returns DROPPED (the
        validation round counter needs the slot)."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cond:
            while True:
                if self._error is not None:
                    raise self._error
                if self._next_emit in self._results:
                    batch = self._results.pop(self._next_emit)
                    self._next_emit += 1
                    self._cond.notify_all()
                    if batch is DROPPED and skip_dropped:
                        continue
                    return batch
                if (self._in_seq is not None
                        and self._next_emit >= self._in_seq):
                    return None          # input exhausted, all emitted
                if self._should_stop():
                    return None
                if deadline is None:
                    wait = 0.2
                else:
                    wait = deadline - time.monotonic()
                    if wait <= 0:
                        raise queue.Empty
                    wait = min(0.2, wait)
                self._cond.wait(wait)

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        while True:
            batch = self.take()
            if batch is None:
                return
            yield batch


class PipelinedFeed:
    """records → FeedQueue → TransformerPool for generator-based
    callers (mini_cluster): a reader thread streams `src` records into
    a bounded feed queue (one mark_epoch_end per epoch, shuffled at
    TRAIN like DataSource.batches), the pool packs them off-thread.
    Iterate for ordered batches; close() tears the threads down."""

    def __init__(self, src, *, loop: bool = True,
                 shuffle: Optional[bool] = None, num_threads: int = 2,
                 metrics=None,
                 should_stop: Optional[Callable[[], bool]] = None,
                 capacity: int = SOURCE_QUEUE_CAPACITY):
        self._closed = False
        ext = should_stop or (lambda: False)
        self.feed = FeedQueue(capacity)
        self._reader_error: dict = {}
        do_shuffle = src.phase_train if shuffle is None else shuffle
        tune_decode_threads(src, num_threads)

        def read():
            # NOTE: mirrors DataSource.batches()'s record loop (shuffle
            # selection, empty-source guard, epoch counting, loop-True
            # tail carry-over) — the pooled-vs-inline parity tests pin
            # the two together; change them in lockstep.  Divergence is
            # loop=False only: batches() yields the ragged tail as a
            # short batch, the pool (fixed batch shapes) drops it.
            epoch = 0
            try:
                while not self._closed and not ext():
                    got_any = False
                    records = (src.shuffled_records(epoch) if do_shuffle
                               else src.records())
                    for rec in records:
                        got_any = True
                        if not self.feed.offer(rec):
                            return
                    if not got_any:
                        return
                    if not loop:
                        # single pass: the ragged tail can't form a
                        # fixed-shape batch — drop it explicitly
                        self.feed.mark_epoch_end()
                        return
                    # looping epochs stream CONTINUOUSLY, matching
                    # DataSource.batches(loop=True): a partial tail
                    # carries into the next epoch's records (no
                    # STOP_MARK — with one, a rank whose shard is
                    # smaller than batch_size would never form a batch
                    # and the consumer would hang)
                    epoch += 1
            except BaseException as e:  # noqa: BLE001 — surfaced below
                self._reader_error["e"] = e
            finally:
                self.feed.offer(None)   # terminal sentinel
                self.feed.stop()

        self.pool = TransformerPool(
            self.feed, src.batch_size,
            pack=src.pack_batch, draw_fn=src.make_draw_fn(),
            num_threads=num_threads, metrics=metrics,
            should_stop=lambda: self._closed or ext())
        self.pool.start()
        self._reader = threading.Thread(target=read, daemon=True,
                                        name="cos-feed-reader")
        self._reader.start()

    def __iter__(self):
        for batch in self.pool:
            yield batch
        err = self._reader_error.get("e")
        if err is not None:
            raise err

    def close(self, join_timeout: Optional[float] = 2.0):
        self._closed = True
        self.feed.stop()
        self.pool.stop(join_timeout=join_timeout)

    def __del__(self):
        # safety net for consumers that abandon iteration without
        # close(): flag the reader/pool threads down so they don't
        # busy-poll for the process lifetime (no join at GC time)
        try:
            self._closed = True
            self.feed.stop()
            self.pool.stop()
        except Exception:               # noqa: BLE001 — interpreter exit
            pass


def combine_batches(batches: Iterator[Dict[str, np.ndarray]], k: int,
                    time_major: frozenset = frozenset()
                    ) -> Iterator[Dict[str, np.ndarray]]:
    """Concatenate k consecutive batches along the batch axis (axis 1
    for time-major keys) — feeds iter_size>1 steps, which consume
    (iter_size·B, ...) per call and split internally
    (solver.train_step_fn)."""
    if k <= 1:
        yield from batches
        return
    buf: list = []
    for b in batches:
        buf.append(b)
        if len(buf) == k:
            yield {key: np.concatenate(
                [x[key] for x in buf],
                axis=1 if key in time_major else 0)
                for key in buf[0]}
            buf = []
    if buf:
        # a short epoch's trailing partial group is discarded by design
        # (static iter_size·B step shapes) — but say so, or it reads as
        # lost data
        _LOG.info(
            "combine_batches: dropping %d trailing sub-batch(es) short "
            "of an iter_size=%d group", len(buf), k)


def chunk_schedule(start_iter: int, max_iter: int, k: int,
                   boundaries=()) -> Iterator[int]:
    """Per-dispatch step counts for the fused multi-step loop: yields
    `k` while the next `k` iterations stay inside every configured
    interval, and falls back to single-step (1) chunks when a boundary
    (`test_interval`, `snapshot`, `display` — zeros are ignored) or
    `max_iter` is closer than `k`.  A chunk may END exactly on a
    boundary (the host-side action runs between dispatches), it never
    spans one — interleaved validation, snapshot cadence and the
    display log keep their exact iterations.

    The schedule is a pure function of (start_iter, config), so a run
    resumed from a snapshot mid-training re-derives the identical
    chunking from the restored iteration.

    Configured-vs-effective visibility: entering a forced-single
    region logs ONCE per boundary (not per chunk)."""
    if k < 1:
        raise ValueError(f"steps-per-loop k must be >= 1, got {k}")
    bset = sorted({int(b) for b in boundaries if b and int(b) > 0})
    it = int(start_iter)
    in_single_run = False
    while max_iter <= 0 or it < max_iter:
        dist = min((b - it % b) for b in bset) if bset else k
        if max_iter > 0:
            dist = min(dist, max_iter - it)
        if dist >= k:
            in_single_run = False
            yield k
            it += k
        else:
            if k > 1 and not in_single_run:
                _LOG.info(
                    "steps_per_loop: boundary at iter %d forces %d "
                    "single-step remainder chunk(s) (configured "
                    "chunk size %d)", it + dist, dist, k)
                in_single_run = True
            yield 1
            it += 1


def stack_chunks(batches: Iterator[Dict[str, np.ndarray]],
                 schedule: Iterator[int], *, metrics=None
                 ) -> Iterator[tuple]:
    """Group per-step batches into `(n, block)` chunks following
    `schedule` (chunk_schedule): n == 1 passes the batch through
    unstacked (the plain-step path), n > 1 stacks n batches along a
    new axis 0 into the (K, batch…) block the fused scan step
    consumes.  `np.stack` copies into a fresh buffer, so chunks are
    immune to the CPU-backend `device_put` host-buffer aliasing
    hazard by construction; single-step chunks keep relying on
    device_prefetch's copy-on-CPU rule.  A stream that ends mid-chunk
    flushes the leftovers as single-step chunks — the single-step
    program is already compiled, odd remainder sizes never are."""
    it = iter(batches)
    for n in schedule:
        if n <= 1:
            try:
                b = next(it)
            except StopIteration:
                return
            yield 1, b
            continue
        buf = []
        for _ in range(n):
            try:
                buf.append(next(it))
            except StopIteration:
                break
        if len(buf) == n:
            t0 = time.perf_counter()
            block = {key: np.stack([b[key] for b in buf])
                     for key in buf[0]}
            if metrics is not None:
                metrics.add("stack", time.perf_counter() - t0)
            yield n, block
        else:
            for b in buf:
                yield 1, b
            return


def chunked_feed(batches: Iterator[Dict[str, np.ndarray]], *,
                 start_iter: int, max_iter: int, k: int,
                 boundaries=(), metrics=None) -> Iterator[tuple]:
    """The (n, batch) stream both train loops consume: K > 1 routes
    through chunk_schedule + stack_chunks, K == 1 passes singles
    through — one place for the schedule construction so the
    CaffeProcessor and mini_cluster trainers cannot drift."""
    if k > 1:
        return stack_chunks(
            batches,
            chunk_schedule(start_iter, max_iter, k, boundaries),
            metrics=metrics)
    return ((1, b) for b in batches)


def _resolve_host_copy(host_copy: Optional[bool]) -> bool:
    """Copy numpy buffers before device_put?  On the CPU backend
    jax.device_put ALIASES aligned host buffers (zero-copy), so a
    pooled/reused pack buffer mutated after staging would corrupt the
    staged batch; accelerator backends copy H2D anyway.  Default: copy
    on CPU only; COS_STAGE_COPY=0/1 overrides."""
    if host_copy is not None:
        return bool(host_copy)
    env = os.environ.get("COS_STAGE_COPY")
    if env is not None:
        return env.lower() not in ("0", "", "false", "no")
    return jax.default_backend() == "cpu"


def device_prefetch(batches: Iterator[Dict[str, np.ndarray]], *,
                    depth: int = 2, sharding=None,
                    device_transforms=None, background: bool = False,
                    metrics=None, host_copy: Optional[bool] = None,
                    chunked: bool = False, chunk_sharding=None
                    ) -> Iterator[Dict[str, jax.Array]]:
    """Asynchronously stage `depth` batches onto the device (the
    double-buffered QueuePair analog). jax transfers are async: calling
    device_put for batch N+1 while N computes overlaps H2D with compute.

    `device_transforms` ({top: fn(u8, aux) -> float}, from
    Source.enable_device_transform) finishes the transform split: the
    uint8 pixels + aux offsets cross the host->device link (4x fewer
    bytes than float32) and the jitted mean/scale stage runs on device,
    dispatched right behind the transfer so it overlaps like the
    transfer itself.  Tops without an aux key pass through untouched.

    With `background=True` the staging itself (device_put dispatch +
    jitted transform dispatch) runs on a dedicated stager thread with a
    bounded handoff queue — the H2D path overlaps compute even when the
    upstream producer (host pack) is slow, and the solver thread only
    ever blocks on a ready-batch queue.  Closing the returned generator
    stops the thread.

    `host_copy` (see _resolve_host_copy) defends staged batches against
    pack-buffer reuse on the aliasing CPU backend.

    With `chunked=True` the upstream yields `(n, batch)` pairs
    (stack_chunks): n == 1 batches stage exactly as before under
    `sharding`, n > 1 blocks stage under `chunk_sharding` (the same
    per-step specs with an unsharded leading chunk axis) and their
    device transforms run vmapped over the chunk axis; the generator
    then yields `(n, staged)`.  Stacked blocks are fresh `np.stack`
    copies, so the copy-on-CPU aliasing defense applies only to the
    n == 1 path.

    Multi-host: when the mesh spans processes, each process's batch is
    its LOCAL shard of the global batch (per-device batch semantics —
    'batch sizes in prototxt files are per device'); the global array is
    assembled with make_array_from_process_local_data."""
    from .transformer import DEVICE_AUX_SUFFIX
    multiproc = jax.process_count() > 1
    jitted = {k: jax.jit(fn)
              for k, fn in (device_transforms or {}).items()}
    vjitted = ({k: jax.jit(jax.vmap(fn))
                for k, fn in (device_transforms or {}).items()}
               if chunked else {})
    copy_host = _resolve_host_copy(host_copy)

    def put_one(v, sh, copy):
        if copy and isinstance(v, np.ndarray):
            v = np.array(v, copy=True)
        if sh is None:
            return jax.device_put(v)
        if multiproc:
            return jax.make_array_from_process_local_data(sh, v)
        return jax.device_put(v, sh)

    def stage_dict(b, sh, fns, copy):
        def sh_for(k):
            if not isinstance(sh, dict):
                return sh
            if k.endswith(DEVICE_AUX_SUFFIX):
                # aux rides its top's batch-dim sharding (P("dp") specs)
                return sh.get(k[:-len(DEVICE_AUX_SUFFIX)])
            return sh[k]  # unknown top = config error: fail fast

        staged = {k: put_one(v, sh_for(k), copy) for k, v in b.items()}
        if not fns:
            return staged
        out = {}
        for k, v in staged.items():
            if k.endswith(DEVICE_AUX_SUFFIX):
                continue
            aux = staged.get(k + DEVICE_AUX_SUFFIX)
            fn = fns.get(k)
            out[k] = fn(v, aux) if (fn is not None
                                    and aux is not None) else v
        return out

    def put(item):
        if not chunked:
            return stage_dict(item, sharding, jitted, copy_host)
        n, b = item
        if n == 1:
            return 1, stage_dict(b, sharding, jitted, copy_host)
        return n, stage_dict(b, chunk_sharding, vjitted, False)

    def timed_put(b):
        t0 = time.perf_counter()
        staged = put(b)
        if metrics is not None:
            metrics.add("stage", time.perf_counter() - t0)
        return staged

    if background:
        return _background_stage(batches, timed_put, depth, metrics)
    return _foreground_stage(batches, timed_put, depth)


def _foreground_stage(batches, timed_put, depth):
    buf = collections.deque()
    for b in batches:
        buf.append(timed_put(b))
        if len(buf) > depth:
            yield buf.popleft()
    while buf:
        yield buf.popleft()


def _background_stage(batches, timed_put, depth, metrics):
    outq: queue.Queue = queue.Queue(maxsize=max(1, depth))
    stop = threading.Event()
    state: dict = {}

    def run():
        try:
            for b in batches:
                staged = timed_put(b)
                if metrics is not None:
                    metrics.gauge("stage_depth", outq.qsize())
                while not stop.is_set():
                    try:
                        outq.put(staged, timeout=0.2)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as e:      # noqa: BLE001 — re-raised below
            state["err"] = e
        finally:
            while not stop.is_set():
                try:
                    outq.put(_END, timeout=0.2)
                    break
                except queue.Full:
                    continue

    def gen():
        # lazy start: the thread exists only once the consumer actually
        # iterates — a generator that is built but never driven (early
        # exit between construction and the first next()) must not leak
        # a stager spinning on a full handoff queue
        t = threading.Thread(target=run, daemon=True, name="cos-stager")
        t.start()
        try:
            while True:
                item = outq.get()
                if item is _END:
                    err = state.get("err")
                    if err is not None:
                        raise err
                    return
                yield item
        finally:
            stop.set()

    return gen()
