"""Bounded-queue feed runtime: the QueuePair / backpressure protocol of
the reference executor, re-expressed for a host→TPU pipeline.

Reference semantics preserved (SURVEY §2.2):
  * bounded source queue, capacity 1024 (`DataSource.scala:67-76`);
  * STOP_MARK sentinel ends an epoch (`CaffeProcessor.scala:205`);
  * `feedQueue` spins `offer` until the solver completes — device→task
    backpressure (`CaffeProcessor.scala:192-198`);
  * double-buffered transformer→solver handoff (QueuePair depth 2,
    `CaffeProcessor.scala:32-35`) — here a device-prefetch depth of 2:
    while the TPU runs step N, batch N+1 is already transferring H2D.
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Callable, Dict, Iterator, Optional

import jax
import numpy as np

from .source import STOP_MARK

SOURCE_QUEUE_CAPACITY = 1024


class FeedQueue:
    """Bounded record queue with STOP_MARK epoch protocol."""

    def __init__(self, capacity: int = SOURCE_QUEUE_CAPACITY):
        self._q: queue.Queue = queue.Queue(maxsize=capacity)
        self._stopped = False

    def offer(self, item, timeout: Optional[float] = None) -> bool:
        """Blocking put with backpressure; returns False if stopped."""
        if self._stopped:
            return False
        while True:
            try:
                self._q.put(item, timeout=timeout or 0.1)
                return True
            except queue.Full:
                if self._stopped:
                    return False
                if timeout is not None:
                    return False

    def reset(self):
        """Re-arm a stopped queue (processor restart) and drop leftovers."""
        self._stopped = False
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                return

    def mark_epoch_end(self):
        self._q.put(STOP_MARK)

    def take(self, timeout: Optional[float] = None):
        return self._q.get(timeout=timeout) if timeout else self._q.get()

    def stop(self):
        self._stopped = True
        try:                     # wake a consumer blocked in take()
            self._q.put_nowait(STOP_MARK)
        except queue.Full:
            pass

    @property
    def stopped(self) -> bool:
        return self._stopped

    def __len__(self):
        return self._q.qsize()


def batch_iterator(feed: FeedQueue, batch_size: int,
                   pack: Callable) -> Iterator[Dict[str, np.ndarray]]:
    """Drain a FeedQueue into packed batches; one epoch per STOP_MARK."""
    buf = []
    while True:
        item = feed.take()
        if item is STOP_MARK:
            if buf:
                yield pack(buf)
            return
        buf.append(item)
        if len(buf) == batch_size:
            yield pack(buf)
            buf = []


def transformer_pool(feed: FeedQueue, batch_size: int, pack: Callable,
                     out: "queue.Queue", num_threads: int = 1):
    """Background transformer threads (transform_thread_per_device
    analog, `CaffeProcessor.scala:54-55`): decode/augment off the
    critical path while the device computes."""
    def run():
        for batch in batch_iterator(feed, batch_size, pack):
            out.put(batch)
        out.put(STOP_MARK)

    threads = [threading.Thread(target=run, daemon=True)
               for _ in range(num_threads)]
    for t in threads:
        t.start()
    return threads


def combine_batches(batches: Iterator[Dict[str, np.ndarray]], k: int,
                    time_major: frozenset = frozenset()
                    ) -> Iterator[Dict[str, np.ndarray]]:
    """Concatenate k consecutive batches along the batch axis (axis 1
    for time-major keys) — feeds iter_size>1 steps, which consume
    (iter_size·B, ...) per call and split internally
    (solver.train_step_fn)."""
    if k <= 1:
        yield from batches
        return
    buf: list = []
    for b in batches:
        buf.append(b)
        if len(buf) == k:
            yield {key: np.concatenate(
                [x[key] for x in buf],
                axis=1 if key in time_major else 0)
                for key in buf[0]}
            buf = []


def device_prefetch(batches: Iterator[Dict[str, np.ndarray]], *,
                    depth: int = 2, sharding=None,
                    device_transforms=None
                    ) -> Iterator[Dict[str, jax.Array]]:
    """Asynchronously stage `depth` batches onto the device (the
    double-buffered QueuePair analog). jax transfers are async: calling
    device_put for batch N+1 while N computes overlaps H2D with compute.

    `device_transforms` ({top: fn(u8, aux) -> float}, from
    Source.enable_device_transform) finishes the transform split: the
    uint8 pixels + aux offsets cross the host->device link (4x fewer
    bytes than float32) and the jitted mean/scale stage runs on device,
    dispatched right behind the transfer so it overlaps like the
    transfer itself.  Tops without an aux key pass through untouched.

    Multi-host: when the mesh spans processes, each process's batch is
    its LOCAL shard of the global batch (per-device batch semantics —
    'batch sizes in prototxt files are per device'); the global array is
    assembled with make_array_from_process_local_data."""
    from .transformer import DEVICE_AUX_SUFFIX
    buf = collections.deque()
    multiproc = jax.process_count() > 1
    jitted = {k: jax.jit(fn) for k, fn in (device_transforms or {}).items()}

    def put_one(v, sh):
        if sh is None:
            return jax.device_put(v)
        if multiproc:
            return jax.make_array_from_process_local_data(sh, v)
        return jax.device_put(v, sh)

    def sh_for(k):
        if not isinstance(sharding, dict):
            return sharding
        if k.endswith(DEVICE_AUX_SUFFIX):
            # aux rides its top's batch-dim sharding (specs are P("dp"))
            return sharding.get(k[:-len(DEVICE_AUX_SUFFIX)])
        return sharding[k]  # unknown top = config error: fail fast

    def put(b):
        staged = {k: put_one(v, sh_for(k)) for k, v in b.items()}
        if not jitted:
            return staged
        out = {}
        for k, v in staged.items():
            if k.endswith(DEVICE_AUX_SUFFIX):
                continue
            aux = staged.get(k + DEVICE_AUX_SUFFIX)
            fn = jitted.get(k)
            out[k] = fn(v, aux) if (fn is not None
                                    and aux is not None) else v
        return out

    for b in batches:
        buf.append(put(b))
        if len(buf) > depth:
            yield buf.popleft()
    while buf:
        yield buf.popleft()
