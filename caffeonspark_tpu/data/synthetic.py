"""Deterministic synthetic classification datasets for tests and benches.

The reference's CI uses real MNIST LMDB fetched by scripts/setup-mnist.sh
(top Makefile:23) — this environment has no egress, so convergence gates
(InterleaveTest.scala:53-55 analog) run on a synthetic task of the same
shape: 10 classes of HxW images, each class a distinct oriented-bar
pattern plus noise, linearly non-trivial but easily separable by a small
convnet."""

from __future__ import annotations

from typing import Iterator, Tuple

import numpy as np


def make_images(n: int, *, channels: int = 1, height: int = 28,
                width: int = 28, num_classes: int = 10, seed: int = 0,
                noise: float = 0.25) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (images[N,C,H,W] float32 in [0,1], labels[N] int32)."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, size=n).astype(np.int32)
    yy, xx = np.mgrid[0:height, 0:width].astype(np.float32)
    imgs = np.zeros((n, channels, height, width), np.float32)
    for i, k in enumerate(labels):
        # oriented sinusoidal grating, angle & frequency indexed by class
        angle = np.pi * k / num_classes
        freq = 2.0 * np.pi * (2 + (k % 3)) / width
        phase = rng.uniform(0, 2 * np.pi)
        pat = 0.5 + 0.5 * np.sin(
            freq * (np.cos(angle) * xx + np.sin(angle) * yy) + phase)
        img = pat + noise * rng.randn(height, width).astype(np.float32)
        imgs[i] = np.clip(img, 0.0, 1.0)[None].repeat(channels, axis=0)
    return imgs, labels


def batches(n: int, batch_size: int, *, seed: int = 0, scale: float = 1.0,
            **kw) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Epoch-less generator of (data, label) batches; data pre-scaled the
    way transform_param.scale would (e.g. 1/256 for MNIST configs)."""
    imgs, labels = make_images(n, seed=seed, **kw)
    # emulate 8-bit storage so transform scale semantics are realistic
    imgs_u8 = (imgs * 255.0).astype(np.float32)
    i = 0
    while True:
        idx = np.arange(i, i + batch_size) % n
        yield imgs_u8[idx] * scale, labels[idx].astype(np.float32)
        i = (i + batch_size) % n
