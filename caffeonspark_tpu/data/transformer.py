"""Host-side data transformer: Caffe `transform_param` semantics.

Equivalent of caffe::DataTransformer<float> consumed through the JNI
wrapper `jcaffe/FloatDataTransformer.java:9-40` (scale / mirror / crop /
mean-subtract per batch, SURVEY §2.4).  Runs on the host CPU over numpy
batches (the TPU analog of the reference's transformer threads feeding
preallocated blobs), so the jitted step receives ready NCHW tensors.

Order of operations (matches Caffe Transform, data_transformer.cpp):
  1. crop (random at TRAIN, center at TEST)
  2. mean_file subtraction at the SOURCE pixel — the mean is cropped at
     the same per-sample (h_off, w_off) as the image, before mirroring
  3. mirror (random horizontal flip at TRAIN)
  4. mean_value per-channel subtraction (commutes with the flip)
  5. scale multiplication
"""

from __future__ import annotations

import threading
from typing import NamedTuple, Optional, Tuple

import numpy as np

from ..proto.caffe import BlobProto, TransformationParameter

# batch-dict key suffix carrying the (N, 3) int32 [h_off, w_off, flip]
# aux array of the device-transform split (see Transformer.host_stage)
DEVICE_AUX_SUFFIX = "__devxf"


class AugDraw(NamedTuple):
    """One batch's pre-drawn augmentation: `offs` is (hs, ws) per-sample
    crop offsets or None when no crop applies, `flip` the per-sample
    mirror flags.  Produced by Transformer.draw() so a multi-threaded
    pack pool can consume the RNG in feed order on ONE thread and hand
    workers a fixed draw — the pooled pipeline then reproduces the
    inline path's augmentation stream exactly."""
    offs: Optional[Tuple[np.ndarray, np.ndarray]]
    flip: np.ndarray


def load_mean_file(path: str) -> np.ndarray:
    """mean.binaryproto → (C, H, W) float32 (BlobProto wire format)."""
    with open(path, "rb") as f:
        bp = BlobProto.from_binary(f.read())
    if bp.shape.dim:
        shape = tuple(int(d) for d in bp.shape.dim)
    else:
        shape = (int(bp.channels), int(bp.height), int(bp.width))
    arr = np.asarray(bp.data, np.float32).reshape(shape)
    if arr.ndim == 4:
        arr = arr[0]
    return arr


class Transformer:
    """Batched NCHW transformer with Caffe RNG discipline: one stream per
    transformer instance, seeded per rank (CaffeNet.cpp:614-618 analog)."""

    def __init__(self, tp: Optional[TransformationParameter], *,
                 phase_train: bool, seed: int = 0,
                 mean_dir: Optional[str] = None):
        self.tp = tp or TransformationParameter()
        self.train = phase_train
        self.rng = np.random.RandomState(seed & 0x7FFFFFFF)
        # np.RandomState is not safe under concurrent draws; draw()
        # serializes consumers (pool dispatcher vs inline callers)
        self._rng_lock = threading.Lock()
        self.mean: Optional[np.ndarray] = None
        if self.tp.has("mean_file") and self.tp.mean_file:
            import os
            p = self.tp.mean_file
            if mean_dir is not None and not os.path.isabs(p):
                p = os.path.join(mean_dir, p)
            self.mean = load_mean_file(p)
        if self.tp.mean_value and self.mean is not None:
            raise ValueError("specify either mean_file or mean_value, "
                             "not both")

    # -- the RNG-bearing draws, shared verbatim by the host-only path and
    # the device-transform split so both consume self.rng identically
    # (trajectory parity between the two pipelines depends on it) -------

    def _draw_crop(self, n: int, h: int, w: int):
        """Per-sample crop offsets, or None when no crop applies.
        Draws from self.rng ONLY at TRAIN with an active crop."""
        crop = int(self.tp.crop_size)
        if not (crop and (crop != h or crop != w)):
            return None
        if crop > h or crop > w:
            raise ValueError(f"crop_size {crop} exceeds input {h}x{w}")
        if self.train:
            hs = self.rng.randint(0, h - crop + 1, size=n)
            ws = self.rng.randint(0, w - crop + 1, size=n)
        else:
            hs = np.full(n, (h - crop) // 2)
            ws = np.full(n, (w - crop) // 2)
        return hs, ws

    def _draw_flip(self, n: int):
        """Per-sample mirror flags (TRAIN with mirror), else all-False."""
        if self.tp.mirror and self.train:
            return self.rng.randint(0, 2, size=n).astype(bool)
        return np.zeros(n, bool)

    def draw(self, n: int, h: int, w: int) -> AugDraw:
        """Consume the RNG for one n-sample batch — crop offsets then
        mirror flags, the exact order __call__/host_stage use — under a
        lock, so a transformer-pool dispatcher can pre-draw batches in
        feed order while workers pack concurrently."""
        with self._rng_lock:
            offs = self._draw_crop(n, h, w)
            flip = self._draw_flip(n)
        return AugDraw(offs, flip)

    def __call__(self, batch: np.ndarray,
                 draw: Optional[AugDraw] = None) -> np.ndarray:
        """batch: (N, C, H, W) float32 (raw 0..255 pixel scale);
        `draw` replays a pre-drawn augmentation instead of consuming
        the RNG here (TransformerPool ordered-draw protocol)."""
        tp = self.tp
        n, c, h, w = batch.shape
        crop = int(tp.crop_size)
        out = batch
        if draw is None:
            draw = self.draw(n, h, w)

        # Caffe subtracts mean_file at the SOURCE index (data_index uses
        # h_off/w_off, mirror only remaps the destination) — equivalent
        # to subtracting the full-size mean BEFORE crop+flip.
        if self.mean is not None:
            m = self.mean
            if m.shape[1] == h and m.shape[2] == w:
                out = out - m[None]
                mean_done = True
            else:
                mean_done = False  # crop-sized mean: subtract post-crop
        else:
            mean_done = True

        offs = draw.offs
        if offs is not None:
            hs, ws = offs
            crop = int(tp.crop_size)
            if self.train:
                out = (np.stack([out[i, :, hs[i]:hs[i] + crop,
                                     ws[i]:ws[i] + crop]
                                 for i in range(n)])
                       if n else
                       np.empty((0, c, crop, crop), out.dtype))
            else:  # center crop: one slice for the whole batch —
                #      scalar offsets, not hs[0] (an empty batch has
                #      no element 0 but still a valid cropped shape)
                h0, w0 = (h - crop) // 2, (w - crop) // 2
                out = out[:, :, h0:h0 + crop, w0:w0 + crop]
        else:
            out = out.copy()

        if not mean_done:
            m = self.mean
            if (m.shape[1] != out.shape[2]
                    or m.shape[2] != out.shape[3]):
                hs0 = (m.shape[1] - out.shape[2]) // 2
                ws0 = (m.shape[2] - out.shape[3]) // 2
                m = m[:, hs0:hs0 + out.shape[2], ws0:ws0 + out.shape[3]]
            out = out - m[None]

        flip = draw.flip
        if flip.any():
            out[flip] = out[flip, :, :, ::-1]

        # mean_file and mean_value are mutually exclusive (checked in
        # __init__); mean_file was already subtracted pre-flip above
        if tp.mean_value:
            mv = np.asarray(list(tp.mean_value), np.float32)
            if len(mv) == 1:
                out = out - mv[0]
            else:
                if len(mv) != c:
                    raise ValueError(
                        f"{len(mv)} mean_values for {c} channels")
                out = out - mv.reshape(1, c, 1, 1)

        if tp.scale != 1.0:
            out = out * tp.scale
        return np.ascontiguousarray(out, np.float32)

    def output_hw(self, h: int, w: int) -> Tuple[int, int]:
        crop = int(self.tp.crop_size)
        return (crop, crop) if crop else (h, w)

    # -- device-side transform (COS_DEVICE_TRANSFORM) ----------------------
    # TPU-first split of the Caffe transform: the host keeps only the
    # RNG-bearing byte moves (crop + mirror, on uint8), and the float
    # work (mean subtraction, scale, dtype) runs inside a jitted stage on
    # the device.  The infeed then carries 1 byte/pixel instead of 4 —
    # 4x less host->device traffic (158 MB -> 40 MB per CaffeNet b256
    # step), which is the dominant feed cost over PCIe or the axon
    # tunnel.  The reference instead transforms to float on CPU and
    # ships float blobs to the GPU (FloatDataTransformer.java:9-40).
    #
    # RNG discipline: host_stage draws crop offsets then mirror flips
    # from self.rng in the SAME order as __call__, so a run with the
    # split enabled consumes the stream identically and the (host crop/
    # mirror, device mean/scale) pipeline reproduces the host-only
    # trajectory exactly (test_device_transform_parity).

    def device_eligible(self, in_h: int, in_w: int) -> bool:
        """The split supports the two mean geometries Caffe produces:
        full-size (subtract-then-crop == per-sample window) and
        output-size (plain broadcast).  Any other mean shape keeps the
        host path (center-crop-the-mean semantics need the pre-crop
        size the device stage doesn't see)."""
        if self.mean is None:
            return True
        oh, ow = self.output_hw(in_h, in_w)
        return tuple(self.mean.shape[1:]) in {(in_h, in_w), (oh, ow)}

    def host_stage(self, batch: np.ndarray,
                   draw: Optional[AugDraw] = None):
        """(N,C,H,W) integral-valued pixels -> (uint8 batch cropped +
        mirrored, aux int32 (N,3) of [h_off, w_off, flip]).  Crop and
        flip come from the same draw() the host-only path uses (or a
        pre-drawn AugDraw in the pooled pipeline), so the two pipelines
        consume self.rng identically.  The byte moves run in the
        threaded native kernel (cos_crop_mirror_u8) when built; numpy
        otherwise — identical output either way (test_native.py
        parity)."""
        n, c, h, w = batch.shape
        crop = int(self.tp.crop_size)
        u8 = batch.astype(np.uint8) if batch.dtype != np.uint8 else batch
        if draw is None:
            draw = self.draw(n, h, w)
        offs = draw.offs
        if offs is not None:
            hs, ws = offs
        else:
            hs = np.zeros(n, np.int64)
            ws = np.zeros(n, np.int64)
        flip = draw.flip
        aux = np.stack([hs, ws, flip.astype(np.int64)],
                       axis=1).astype(np.int32)

        from .. import native
        if native.available():
            out = native.crop_mirror_u8(
                u8, hs, ws, flip,
                crop=crop if offs is not None else 0)
            return out, aux

        if offs is not None:
            u8 = np.stack([u8[i, :, hs[i]:hs[i] + crop,
                              ws[i]:ws[i] + crop] for i in range(n)])
        else:
            u8 = u8.copy()
        if flip.any():
            u8[flip] = u8[flip, :, :, ::-1]
        return np.ascontiguousarray(u8), aux

    def device_stage_fn(self, out_dtype=None):
        """Jittable (x_uint8, aux) -> transformed float batch, closing
        over the mean/scale constants.  Subtracting the per-sample
        (h_off, w_off) window of the full-size mean, flipped where the
        image was flipped, is algebraically identical to Caffe's
        subtract-at-source-pixel-then-crop-and-mirror order
        (data_transformer.cpp; see __call__'s comments)."""
        import jax
        import jax.numpy as jnp

        tp = self.tp
        mean = self.mean
        mv = np.asarray(list(tp.mean_value), np.float32) \
            if tp.mean_value else None
        scale = float(tp.scale)

        def apply(x, aux):
            out = x.astype(jnp.float32)
            n, c, ch, cw = x.shape
            if mean is not None:
                m = jnp.asarray(mean, jnp.float32)
                if m.shape[1] == ch and m.shape[2] == cw:
                    win = jnp.broadcast_to(m[None], (n,) + m.shape)
                else:
                    # full-size mean (device_eligible guarantees it):
                    # per-sample window at the image's own crop offset
                    def window(a):
                        return jax.lax.dynamic_slice(
                            m, (0, a[0], a[1]), (m.shape[0], ch, cw))
                    win = jax.vmap(window)(aux)
                flip = aux[:, 2].astype(bool)[:, None, None, None]
                win = jnp.where(flip, win[..., ::-1], win)
                out = out - win
            if mv is not None:
                if len(mv) == 1:
                    out = out - mv[0]
                else:
                    out = out - mv.reshape(1, c, 1, 1)
            if scale != 1.0:
                out = out * scale
            if out_dtype is not None:
                out = out.astype(out_dtype)
            return out

        return apply
