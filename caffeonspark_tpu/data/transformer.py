"""Host-side data transformer: Caffe `transform_param` semantics.

Equivalent of caffe::DataTransformer<float> consumed through the JNI
wrapper `jcaffe/FloatDataTransformer.java:9-40` (scale / mirror / crop /
mean-subtract per batch, SURVEY §2.4).  Runs on the host CPU over numpy
batches (the TPU analog of the reference's transformer threads feeding
preallocated blobs), so the jitted step receives ready NCHW tensors.

Order of operations (matches Caffe Transform, data_transformer.cpp):
  1. crop (random at TRAIN, center at TEST)
  2. mean_file subtraction at the SOURCE pixel — the mean is cropped at
     the same per-sample (h_off, w_off) as the image, before mirroring
  3. mirror (random horizontal flip at TRAIN)
  4. mean_value per-channel subtraction (commutes with the flip)
  5. scale multiplication
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..proto.caffe import BlobProto, TransformationParameter


def load_mean_file(path: str) -> np.ndarray:
    """mean.binaryproto → (C, H, W) float32 (BlobProto wire format)."""
    with open(path, "rb") as f:
        bp = BlobProto.from_binary(f.read())
    if bp.shape.dim:
        shape = tuple(int(d) for d in bp.shape.dim)
    else:
        shape = (int(bp.channels), int(bp.height), int(bp.width))
    arr = np.asarray(bp.data, np.float32).reshape(shape)
    if arr.ndim == 4:
        arr = arr[0]
    return arr


class Transformer:
    """Batched NCHW transformer with Caffe RNG discipline: one stream per
    transformer instance, seeded per rank (CaffeNet.cpp:614-618 analog)."""

    def __init__(self, tp: Optional[TransformationParameter], *,
                 phase_train: bool, seed: int = 0,
                 mean_dir: Optional[str] = None):
        self.tp = tp or TransformationParameter()
        self.train = phase_train
        self.rng = np.random.RandomState(seed & 0x7FFFFFFF)
        self.mean: Optional[np.ndarray] = None
        if self.tp.has("mean_file") and self.tp.mean_file:
            import os
            p = self.tp.mean_file
            if mean_dir is not None and not os.path.isabs(p):
                p = os.path.join(mean_dir, p)
            self.mean = load_mean_file(p)
        if self.tp.mean_value and self.mean is not None:
            raise ValueError("specify either mean_file or mean_value, "
                             "not both")

    def __call__(self, batch: np.ndarray) -> np.ndarray:
        """batch: (N, C, H, W) float32 (raw 0..255 pixel scale)."""
        tp = self.tp
        n, c, h, w = batch.shape
        crop = int(tp.crop_size)
        out = batch

        # Caffe subtracts mean_file at the SOURCE index (data_index uses
        # h_off/w_off, mirror only remaps the destination) — equivalent
        # to subtracting the full-size mean BEFORE crop+flip.
        if self.mean is not None:
            m = self.mean
            if m.shape[1] == h and m.shape[2] == w:
                out = out - m[None]
                mean_done = True
            else:
                mean_done = False  # crop-sized mean: subtract post-crop
        else:
            mean_done = True

        if crop and (crop != h or crop != w):
            if crop > h or crop > w:
                raise ValueError(f"crop_size {crop} exceeds input {h}x{w}")
            if self.train:
                hs = self.rng.randint(0, h - crop + 1, size=n)
                ws = self.rng.randint(0, w - crop + 1, size=n)
                out = np.stack([out[i, :, hs[i]:hs[i] + crop,
                                    ws[i]:ws[i] + crop]
                                for i in range(n)])
            else:
                hs0 = (h - crop) // 2
                ws0 = (w - crop) // 2
                out = out[:, :, hs0:hs0 + crop, ws0:ws0 + crop]
        elif crop:
            out = out.copy()
        else:
            out = out.copy()

        if not mean_done:
            m = self.mean
            if (m.shape[1] != out.shape[2]
                    or m.shape[2] != out.shape[3]):
                hs0 = (m.shape[1] - out.shape[2]) // 2
                ws0 = (m.shape[2] - out.shape[3]) // 2
                m = m[:, hs0:hs0 + out.shape[2], ws0:ws0 + out.shape[3]]
            out = out - m[None]

        if tp.mirror and self.train:
            flip = self.rng.randint(0, 2, size=n).astype(bool)
            out[flip] = out[flip, :, :, ::-1]

        # mean_file and mean_value are mutually exclusive (checked in
        # __init__); mean_file was already subtracted pre-flip above
        if tp.mean_value:
            mv = np.asarray(list(tp.mean_value), np.float32)
            if len(mv) == 1:
                out = out - mv[0]
            else:
                if len(mv) != c:
                    raise ValueError(
                        f"{len(mv)} mean_values for {c} channels")
                out = out - mv.reshape(1, c, 1, 1)

        if tp.scale != 1.0:
            out = out * tp.scale
        return np.ascontiguousarray(out, np.float32)

    def output_hw(self, h: int, w: int) -> Tuple[int, int]:
        crop = int(self.tp.crop_size)
        return (crop, crop) if crop else (h, w)
