"""Prometheus exposition of the PipelineMetrics JSON summary.

The summary dict (`PipelineMetrics.summary()` — the exact JSON the
trainer dumps and every serving `/metrics` answers) renders into
text exposition format (`/metrics?format=prom`), so the counters,
percentile rings, and gauges the repo already keeps become scrapeable
without a second bookkeeping path:

  counters      -> `cos_<name>_total` counter
  stage series  -> ONE family per statistic with a `stage` label:
                   `cos_stage_seconds_total` / `cos_stage_calls_total`
                   (counters) and `cos_stage_ms{quantile=...}` /
                   `cos_stage_ms_max` / `cos_stage_ms_mean` (gauges)
  gauges        -> `cos_gauge_mean` / `cos_gauge_max` /
                   `cos_gauge_samples_total` with a `name` label
  steps         -> `cos_steps_total`; steady_steps_per_sec, uptime
                   (`cos_uptime_seconds`), queue_depth_now,
                   model_version -> plain gauges
  build_info    -> `cos_build_info` info-gauge (value 1; net digest /
                   serve mesh / weight dtype / pid as labels — with
                   uptime, the restart detector for scrape-based
                   error budgets)
  router table  -> `cos_replica_up{replica,state}` /
                   `cos_replica_outstanding` /
                   `cos_replica_requests_total` / ..._failures_total /
                   ..._restarts_total

Label-parameterizing the families (stage/name/replica/model — plus a
caller-supplied base label set like `{"replica": "replica0"}` for the
router's fleet aggregation) keeps the family NAME set fixed, so two
summaries merged into one scrape can never emit a duplicate family
header — the thing real scrapers reject.

`parse_exposition` is the round-trip validator the tests and the
bench use: it re-parses rendered output, failing on duplicate
families, type-less samples, or malformed lines.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Tuple

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")
_VALID_FAMILY = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def sanitize(name: str) -> str:
    """Metric/label-value-safe identifier from an arbitrary counter or
    stage name (`flush_bucket_8`, `page_in_modelA`)."""
    out = _NAME_RE.sub("_", str(name))
    if out and out[0].isdigit():
        out = "_" + out
    return out or "_"


class PromWriter:
    """Accumulates samples by family; families are declared once with
    a type, samples append under them — merging any number of
    summaries (fleet aggregation) without duplicate headers."""

    def __init__(self, prefix: str = "cos"):
        self.prefix = prefix
        # family -> (type, help); insertion-ordered
        self._families: Dict[str, Tuple[str, str]] = {}
        self._samples: Dict[str, List[Tuple[Dict[str, str], float]]] = {}

    def family(self, name: str, ftype: str, help_text: str) -> str:
        full = f"{self.prefix}_{name}"
        prev = self._families.get(full)
        if prev is not None and prev[0] != ftype:
            raise ValueError(f"family {full}: type conflict "
                             f"{prev[0]} vs {ftype}")
        if prev is None:
            self._families[full] = (ftype, help_text)
            self._samples[full] = []
        return full

    def sample(self, name: str, ftype: str, help_text: str,
               value: float, labels: Optional[Dict[str, str]] = None
               ) -> None:
        full = self.family(name, ftype, help_text)
        v = float(value)
        if math.isnan(v) or math.isinf(v):
            return
        self._samples[full].append((dict(labels or {}), v))

    # -- summary ingestion ---------------------------------------------
    def add_summary(self, summary: dict,
                    labels: Optional[Dict[str, str]] = None) -> None:
        """One PipelineMetrics summary's counters/series/gauges, every
        sample carrying `labels` (the router adds {"replica": name})."""
        base = dict(labels or {})

        for cname, v in (summary.get("counters") or {}).items():
            self.sample(f"{sanitize(cname)}_total", "counter",
                        f"counter {cname}", v, base)
        for stage, st in (summary.get("stages") or {}).items():
            sl = dict(base, stage=sanitize(stage))
            self.sample("stage_seconds_total", "counter",
                        "per-stage accumulated seconds",
                        st.get("total_s", 0.0), sl)
            self.sample("stage_calls_total", "counter",
                        "per-stage sample count",
                        st.get("count", 0), sl)
            self.sample("stage_ms_mean", "gauge",
                        "per-stage mean milliseconds",
                        st.get("mean_ms", 0.0), sl)
            self.sample("stage_ms_max", "gauge",
                        "per-stage max milliseconds",
                        st.get("max_ms", 0.0), sl)
            for q, key in (("0.5", "p50_ms"), ("0.95", "p95_ms"),
                           ("0.99", "p99_ms"), ("0.999", "p99_9_ms")):
                self.sample("stage_ms", "gauge",
                            "per-stage latency quantiles (ms, over "
                            "the bounded sample ring)",
                            st.get(key, 0.0), dict(sl, quantile=q))
        for gname, g in (summary.get("queue_depths") or {}).items():
            gl = dict(base, name=sanitize(gname))
            self.sample("gauge_mean", "gauge", "sampled gauge mean",
                        g.get("mean", 0.0), gl)
            self.sample("gauge_max", "gauge", "sampled gauge max",
                        g.get("max", 0.0), gl)
            self.sample("gauge_samples_total", "counter",
                        "sampled gauge observation count",
                        g.get("samples", 0), gl)
        if "steps" in summary:
            self.sample("steps_total", "counter",
                        "completed solver steps", summary["steps"],
                        base)
        bi = summary.get("build_info")
        if bi:
            # info-gauge (value pinned to 1, identity rides in the
            # labels): with cos_uptime_seconds this is how scrape-based
            # error-budget accounting detects a replica RESTART between
            # scrapes — pid/net-digest label change or uptime decrease
            # — instead of misreading the counter reset as a negative
            # rate
            self.sample("build_info", "gauge",
                        "process identity info-gauge (value always 1; "
                        "net digest / serve mesh / weight dtype / pid "
                        "ride as labels)", 1.0,
                        dict(base, **{str(k): str(v)
                                      for k, v in bi.items()}))
        for key, fam, help_text in (
                ("uptime_s", "uptime_seconds", "process uptime"),
                ("steady_steps_per_sec", "steady_steps_per_sec",
                 "steady-state steps/sec (warmup-skipped)"),
                ("queue_depth_now", "queue_depth_now",
                 "live batcher queue depth (all lanes)"),
                ("model_version", "model_version",
                 "current default-model version"),
                ("warmup_s", "warmup_seconds", "warmup wall time"),
                ("hbm_budget_mb", "hbm_budget_mb",
                 "serving HBM budget (MB)")):
            if summary.get(key) is not None:
                self.sample(fam, "gauge", help_text, summary[key],
                            base)
        for mname, st in (summary.get("models") or {}).items():
            ml = dict(base, model=sanitize(mname))
            self.sample("model_resident", "gauge",
                        "1 = model resident in HBM",
                        1.0 if st.get("resident") else 0.0, ml)
            for sg in (st.get("stages") or []):
                self.sample("stage_resident", "gauge",
                            "1 = pipeline stage resident in HBM",
                            1.0 if sg.get("resident") else 0.0,
                            dict(ml, stage=str(sg.get("stage"))))
            for k in ("requests", "rows", "evictions", "page_ins"):
                if st.get(k) is not None:
                    self.sample(f"model_{k}_total", "counter",
                                f"per-model {k}", st[k], ml)
            if st.get("p99_ms") is not None:
                self.sample("model_p99_ms", "gauge",
                            "per-model p99 latency (ms)",
                            st["p99_ms"], ml)
        for rname, st in (summary.get("replicas") or {}).items():
            rl = dict(base, replica=sanitize(rname))
            # multi-host fleets label every replica sample with the
            # NodeAgent host carrying it; local fleets stay unlabeled
            if st.get("host"):
                rl["host"] = sanitize(st["host"])
            self.sample("replica_up", "gauge",
                        "1 = replica routable (state=ok)",
                        1.0 if st.get("state") == "ok" else 0.0,
                        dict(rl, state=sanitize(st.get("state",
                                                       "unknown"))))
            self.sample("replica_outstanding", "gauge",
                        "router-side in-flight requests",
                        st.get("outstanding", 0), rl)
            for k in ("requests", "failures", "restarts"):
                self.sample(f"replica_{k}_total", "counter",
                            f"per-replica {k}", st.get(k, 0), rl)
            # the hedging budget's inputs: router-observed per-replica
            # success latency (EWMA + ring p95) — why a hedge fired
            for k, fam in (("lat_ewma_ms", "replica_lat_ewma_ms"),
                           ("lat_p95_ms", "replica_lat_p95_ms")):
                if st.get(k) is not None:
                    self.sample(fam, "gauge",
                                "router-observed replica latency (ms)",
                                st[k], rl)
        # NodeAgent heartbeat view (Fleet._agents_once): one gauge per
        # host so an alert fires the moment an agent stops answering
        for hname, st in (summary.get("hosts") or {}).items():
            self.sample("host_up", "gauge",
                        "1 = NodeAgent heartbeat answering",
                        1.0 if st.get("up") else 0.0,
                        dict(base, host=sanitize(hname)))
        # fleet control plane: the autoscaler's own actuation signal
        # (cos_fleet_size is what a dashboard overlays on qdepth/p99
        # to SEE the controller react)
        fl = summary.get("fleet")
        if fl:
            if fl.get("size") is not None:
                self.sample("fleet_size", "gauge",
                            "replicas in the routing table",
                            fl["size"], base)
            if fl.get("routable") is not None:
                self.sample("fleet_routable", "gauge",
                            "replicas currently routable (state=ok)",
                            fl["routable"], base)
            for k in ("scale_ups", "scale_downs", "restarts"):
                if fl.get(k) is not None:
                    self.sample(f"fleet_{k}_total", "counter",
                                f"fleet {k}", fl[k], base)
        # admission lanes: depth gauge + outcome counters per priority
        # class — the starvation check is cos_lane_forwarded_total
        # {lane="batch"} rising while interactive p99 holds
        for lname, st in (summary.get("lanes") or {}).items():
            ll = dict(base, lane=sanitize(lname))
            self.sample("lane_depth", "gauge",
                        "rows queued in the admission lane",
                        st.get("depth", 0), ll)
            for k, v in st.items():
                # lifetime outcome counters ride flat in the block
                # (lanes_summary): everything but the live gauges
                if k in ("depth", "entries") \
                        or not isinstance(v, (int, float)):
                    continue
                self.sample(f"lane_{sanitize(k)}_total", "counter",
                            f"admission lane {k}", v, ll)

    # -- rendering -----------------------------------------------------
    def render(self) -> str:
        lines: List[str] = []
        for fam, (ftype, help_text) in self._families.items():
            samples = self._samples[fam]
            if not samples:
                continue
            lines.append(f"# HELP {fam} {help_text}")
            lines.append(f"# TYPE {fam} {ftype}")
            for labels, value in samples:
                if labels:
                    lab = ",".join(
                        f'{k}="{_escape(v)}"'
                        for k, v in sorted(labels.items()))
                    lines.append(f"{fam}{{{lab}}} {_fmt(value)}")
                else:
                    lines.append(f"{fam} {_fmt(value)}")
        return "\n".join(lines) + "\n"


def _escape(v) -> str:
    return (str(v).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _fmt(v: float) -> str:
    if float(v).is_integer() and abs(v) < 2**53:
        return str(int(v))
    return repr(float(v))


def render_summary(summary: dict,
                   labels: Optional[Dict[str, str]] = None) -> str:
    w = PromWriter()
    w.add_summary(summary, labels)
    return w.render()


# -- validity (the round-trip the tests pin) ----------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+(-?[0-9.eE+-]+|NaN)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_exposition(text: str) -> Dict[str, dict]:
    """Strict-enough exposition parser: returns
    {family: {"type", "help", "samples": [(labels, value), ...]}}.
    Raises ValueError on duplicate family declarations, samples with
    no TYPE, label-syntax garbage, or unparseable lines — the checks
    a real scraper's rejection would surface in production."""
    fams: Dict[str, dict] = {}
    declared: set = set()
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            name = line.split()[2]
            fams.setdefault(name, {"type": None, "help": None,
                                   "samples": []})
            fams[name]["help"] = line.split(" ", 3)[3] \
                if len(line.split(" ", 3)) > 3 else ""
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            name, ftype = parts[2], parts[3]
            if name in declared:
                raise ValueError(
                    f"line {lineno}: duplicate TYPE for family {name}")
            declared.add(name)
            fams.setdefault(name, {"type": None, "help": None,
                                   "samples": []})
            fams[name]["type"] = ftype
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: unparseable sample "
                             f"{line!r}")
        name, _, labelstr, value = m.groups()
        if name not in fams or fams[name]["type"] is None:
            raise ValueError(f"line {lineno}: sample for undeclared "
                             f"family {name}")
        labels: Dict[str, str] = {}
        if labelstr:
            consumed = sum(len(mm.group(0))
                           for mm in _LABEL_RE.finditer(labelstr))
            stripped = labelstr.replace(",", "").replace(" ", "")
            if consumed < len(stripped):
                raise ValueError(f"line {lineno}: bad label syntax "
                                 f"{labelstr!r}")
            labels = {mm.group(1): mm.group(2)
                      for mm in _LABEL_RE.finditer(labelstr)}
        fams[name]["samples"].append((labels, float(value)))
    for name, fam in fams.items():
        if not _VALID_FAMILY.match(name):
            raise ValueError(f"bad family name {name!r}")
    return fams


def counter_values(fams: Dict[str, dict]) -> Dict[str, float]:
    """Flattened {family{sorted-labels}: value} for every counter
    family — what the monotonicity check compares across scrapes."""
    out: Dict[str, float] = {}
    for name, fam in fams.items():
        if fam["type"] != "counter":
            continue
        for labels, value in fam["samples"]:
            key = name + "|" + ",".join(
                f"{k}={v}" for k, v in sorted(labels.items()))
            out[key] = value
    return out
