"""Training-side metrics port (COS_METRICS_PORT).

Serving replicas and the fleet router have always had `/metrics`; the
trainer only dumped its PipelineMetrics at exit.  This tiny server
gives a LIVE training process the same scrapeable surface:

  GET  /healthz               {"ok": true, "role": "trainer"}
  GET  /metrics               PipelineMetrics summary (JSON)
  GET  /metrics?format=prom   Prometheus exposition (obs/prom.py)
  GET  /v1/traces[?trace=]    this process's finished spans
  POST /v1/profile            bounded jax.profiler capture
                              (obs/profiler.py) on the live trainer

It reuses the serving JsonHandler (one Content-Length framing
implementation repo-wide) and binds loopback by default — same
exposure stance as the serving servers.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Callable, Optional

from ..utils.envutils import env_int
from .prom import render_summary

_LOG = logging.getLogger(__name__)


def _make_handler():
    # the serving JsonHandler carries the shared framing + the
    # /v1/profile and /v1/traces implementations; imported lazily so
    # obs never drags the serving package in at import time
    from ..serving.http_server import JsonHandler

    class Handler(JsonHandler):
        log_prefix = "obs http: "

        def do_GET(self):
            path, q = self._route()
            if path == "/healthz":
                self._send(200, {"ok": True,
                                 "role": self.server.role})
            elif path == "/metrics":
                summary = self.server.metrics_fn()
                if q.get("format") == "prom":
                    self._send_text(200, render_summary(
                        summary, {"role": self.server.role}))
                else:
                    self._send(200, summary)
            elif path == "/v1/traces":
                self._handle_traces(q)
            else:
                self._send(404, {"error": f"no route {path}"})

        def do_POST(self):
            path, _q = self._route()
            if path == "/v1/profile":
                self._handle_profile()
            else:
                self._send(404, {"error": f"no route {path}"})

    return Handler


class ObsHTTPServer:
    """Bind-and-go metrics/trace/profile surface over a summary
    callable; port 0 picks an ephemeral port (read `.port` back)."""

    def __init__(self, metrics_fn: Callable[[], dict], *,
                 host: str = "127.0.0.1", port: int = 0,
                 role: str = "trainer"):
        from http.server import ThreadingHTTPServer
        self._httpd = ThreadingHTTPServer((host, port), _make_handler())
        self._httpd.daemon_threads = True
        self._httpd.metrics_fn = metrics_fn
        self._httpd.role = role
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start_background(self) -> "ObsHTTPServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="cos-obs-http", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        self._httpd.server_close()


def maybe_start_obs_server(metrics_fn: Callable[[], dict],
                           role: str = "trainer"
                           ) -> Optional[ObsHTTPServer]:
    """COS_METRICS_PORT=N starts the server on port N (0 = ephemeral;
    unset/absent = disabled — the historical no-port behavior)."""
    port_s = os.environ.get("COS_METRICS_PORT")
    if port_s is None or port_s == "":
        return None
    port = env_int("COS_METRICS_PORT", 0, strict=False)
    try:
        srv = ObsHTTPServer(metrics_fn, port=max(0, port),
                            role=role).start_background()
    except OSError as e:
        # an observability knob must never take training down: a port
        # conflict (second trainer on the box, a relaunch racing its
        # not-yet-exited predecessor) warns and runs without the port
        _LOG.warning("obs: COS_METRICS_PORT=%s bind failed (%s) — "
                     "metrics port disabled for this run", port_s, e)
        return None
    _LOG.info("obs: metrics port up on %d (role=%s)", srv.port, role)
    print(json.dumps({"obs_metrics_port": srv.port, "role": role}),
          flush=True)
    return srv
