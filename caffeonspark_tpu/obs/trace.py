"""Distributed request tracing: trace/span ids over the serving hops.

One slow request through the fleet decomposes into WHICH hop ate the
latency: the router mints (or adopts, from the client's `X-COS-Trace`
header) a trace id, opens a span per routing attempt (a retried
request is ONE trace with N attempt spans, never N orphan traces),
and forwards the context to the replica, whose handler, batcher, and
forward hook each contribute child spans:

    router.request              client-observed wall at the router
      router.attempt            one per pick (attrs: replica, outcome)
        replica.request         replica-side wall (parse -> respond)
          serve.queue_wait      submit -> flush pickup (the "RPC
                                Considered Harmful" queueing term)
          serve.pack            flush assembly: decode/transform/pad
          serve.fwd             jitted forward dispatch + row fetch
          serve.exec            whole-flush execution (attrs: bucket,
                                batch — padding visible as bucket-real)

Sampling (`COS_TRACE_SAMPLE`, default 0) is resolved ONCE per process
(COS003 discipline).  0 is INERT: `span()` returns a no-op whose cost
is one attribute check and one thread-local read — the serving hot
path is byte-identical with tracing off.  An inbound sampled header
always wins over the local rate, so a trace stays whole across hops
whatever each process's own sampling says.

Finished spans land in a bounded in-memory ring (served by
`GET /v1/traces`; the router aggregates rings across replicas) and,
when `COS_TRACE_DIR` names a directory, in a per-process JSONL spool
`trace-<pid>.jsonl` that survives the process.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import random
import threading
import time
from collections import deque
from typing import Dict, List, NamedTuple, Optional

from ..utils.envutils import env_num

_LOG = logging.getLogger(__name__)

TRACE_HEADER = "X-COS-Trace"


class SpanCtx(NamedTuple):
    """Wire-propagatable span identity: what a child names as parent."""
    trace_id: str
    span_id: str

    def to_header(self) -> str:
        return f"{self.trace_id}:{self.span_id}"


def parse_header(value: Optional[str]) -> Optional[SpanCtx]:
    """`X-COS-Trace: <trace_id>:<span_id>` -> SpanCtx; None/garbage ->
    None (an unparseable header must never fail a predict)."""
    if not value:
        return None
    parts = value.strip().split(":")
    if len(parts) != 2 or not parts[0] or not parts[1]:
        return None
    return SpanCtx(parts[0], parts[1])


def _new_id(nbits: int = 64) -> str:
    return f"{random.getrandbits(nbits):0{nbits // 4}x}"


class _NullSpan:
    """The inert span: every operation is a no-op, `ctx` is None so
    downstream propagation (headers, request slots) stays absent."""

    __slots__ = ()
    ctx = None

    def set(self, key, value):
        return self

    def header(self) -> Optional[str]:
        return None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __bool__(self):
        return False


NULL_SPAN = _NullSpan()


class Span:
    """One live span; finishes into a compact ring record on exit.
    The hot path stays allocation-light: attrs dict is created only
    on the first set(), the record is a tuple rendered to a dict only
    when read (recent()/spool drain) — finishing a span is a couple
    of clock reads and one locked list-slot write."""

    __slots__ = ("tracer", "name", "ctx", "parent_id", "_t0",
                 "_ts", "attrs")

    def __init__(self, tracer: "Tracer", name: str,
                 trace_id: str, parent_id: Optional[str]):
        self.tracer = tracer
        self.name = name
        self.ctx = SpanCtx(trace_id, tracer._next_span_id())
        self.parent_id = parent_id
        self._t0 = time.monotonic()
        self._ts = time.time()
        self.attrs: Optional[Dict[str, object]] = None

    def set(self, key, value):
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value
        return self

    def header(self) -> str:
        return self.ctx.to_header()

    def __enter__(self):
        self.tracer._push(self.ctx)
        return self

    def __exit__(self, exc_type, exc, tb):
        self.tracer._pop()
        if exc is not None:
            self.set("error", f"{type(exc).__name__}: {exc}")
        self.tracer._finish(self, time.monotonic() - self._t0)
        return False

    def __bool__(self):
        return True


class Tracer:
    """Per-process tracer: sampling decision, thread-local span stack,
    bounded finished-span ring, optional JSONL spool."""

    def __init__(self, service: str = "", *,
                 sample: Optional[float] = None,
                 spool_dir: Optional[str] = None,
                 capacity: int = 4096):
        self.service = service or f"pid{os.getpid()}"
        self.sample = (sample if sample is not None
                       else max(0.0, min(1.0, env_num(
                           "COS_TRACE_SAMPLE", 0.0, strict=False))))
        self.spool_dir = (spool_dir if spool_dir is not None
                          else os.environ.get("COS_TRACE_DIR", ""))
        self._cap = max(16, capacity)
        self._lock = threading.Lock()
        # finish path: ONE GIL-atomic deque.append, no lock — the
        # executor thread is the serving bottleneck and every
        # microsecond of span bookkeeping on it is amplified into
        # request latency.  Readers (recent(), the spool drainer)
        # absorb the staged records into the ring under the lock.
        self._staged: "deque[tuple]" = deque(maxlen=2 * self._cap)
        # ring of COMPACT tuples (trace, span, parent, name, ts, dur,
        # attrs) — rendered to dicts only when read; deque(maxlen)
        # keeps it bounded AND chronological with no index juggling
        self._ring: "deque[tuple]" = deque(maxlen=self._cap)
        self._local = threading.local()
        self._rng = random.Random()
        # span ids: per-process random prefix + cheap counter — unique
        # across the fleet without a 64-bit RNG draw per span
        self._id_prefix = f"{random.getrandbits(32):08x}"
        self._id_counter = itertools.count(1)
        # spool: absorbed records buffer here; the background drainer
        # serializes + writes them OFF the request path
        self._pending: List[tuple] = []
        self._spool = None          # lazily-opened JSONL handle
        self._spool_path: Optional[str] = None
        # serializes open/write/close of the spool handle: the 0.2s
        # drainer and a shutdown-path flush_spool() (or reconfigure)
        # may drain concurrently, and two buffered handles appending
        # to one file would interleave mid-line
        self._spool_lock = threading.Lock()
        self._drainer: Optional[threading.Thread] = None
        self._drain_stop = threading.Event()

    def _next_span_id(self) -> str:
        return f"{self._id_prefix}{next(self._id_counter):07x}"

    # -- sampling / context --------------------------------------------
    def enabled(self) -> bool:
        return self.sample > 0.0

    def sample_root(self) -> bool:
        """One sampling draw — True means this process roots a new
        trace for the request it is looking at."""
        if self.sample <= 0.0:
            return False
        return self.sample >= 1.0 or self._rng.random() < self.sample

    def from_header(self, value: Optional[str]) -> Optional[SpanCtx]:
        return parse_header(value)

    def _stack(self) -> List[SpanCtx]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current(self) -> Optional[SpanCtx]:
        st = getattr(self._local, "stack", None)
        return st[-1] if st else None

    def _push(self, ctx: SpanCtx) -> None:
        self._stack().append(ctx)

    def _pop(self) -> None:
        st = getattr(self._local, "stack", None)
        if st:
            st.pop()

    def activate(self, ctx: Optional[SpanCtx]):
        """Context manager installing `ctx` as the thread's current
        parent — the cross-thread handoff (a batcher executor thread
        adopting a request's context so the model hook's spans nest
        under it).  None -> no-op."""
        return _Activation(self, ctx) if ctx is not None else NULL_SPAN

    # -- span creation -------------------------------------------------
    def span(self, name: str, parent: Optional[SpanCtx] = None,
             root: bool = False):
        """Open a span.  Parent resolution: explicit `parent` wins,
        else the thread's current span, else a new root when `root`
        (the caller's sampling draw said yes).  No parent and no root
        -> the inert NULL_SPAN (tracing-off hot path)."""
        if parent is None:
            parent = self.current()
        if parent is not None:
            return Span(self, name, parent.trace_id, parent.span_id)
        if root:
            return Span(self, name, _new_id(), None)
        return NULL_SPAN

    def record_span(self, name: str, parent: Optional[SpanCtx],
                    duration_s: float, **attrs) -> None:
        """Record an already-measured interval as a finished span
        (the batcher back-dates queue-wait from the request's submit
        timestamp).  No-op when parent is None."""
        if parent is None:
            return
        rec = (parent.trace_id, self._next_span_id(), parent.span_id,
               name, time.time() - duration_s, duration_s,
               attrs or None)
        self._store(rec)

    # -- finished spans ------------------------------------------------
    def _finish(self, span: Span, duration_s: float) -> None:
        self._store((span.ctx.trace_id, span.ctx.span_id,
                     span.parent_id, span.name, span._ts, duration_s,
                     span.attrs))

    def _store(self, rec: tuple) -> None:
        # hot path: one atomic append.  The bounded deque guarantees
        # memory even if nothing ever reads; sustained bursts past
        # 2x capacity between absorptions drop oldest (ring
        # semantics anyway).
        self._staged.append(rec)
        if self.spool_dir and self._drainer is None:
            with self._lock:
                if self._drainer is None:
                    self._start_drainer_locked()

    def _absorb_staged(self) -> None:
        """Move staged records into the ring (and the spool-pending
        buffer) — reader-side work, never the request path."""
        with self._lock:
            while True:
                try:
                    rec = self._staged.popleft()
                except IndexError:
                    break
                self._ring.append(rec)
                if self.spool_dir:
                    self._pending.append(rec)

    def _rec_to_dict(self, rec: tuple) -> dict:
        out = {"trace_id": rec[0], "span_id": rec[1],
               "parent_id": rec[2], "name": rec[3],
               "service": self.service,
               "ts": round(rec[4], 6),
               "dur_ms": round(rec[5] * 1e3, 4)}
        if rec[6]:
            out["attrs"] = dict(rec[6])
        return out

    def _rec_to_line(self, rec: tuple) -> str:
        """One JSONL line, hand-assembled: ids/names are [0-9a-zA-Z._-]
        by construction so only the attrs dict (rare) pays a real
        json.dumps — the drainer serializes thousands of spans per
        second and generic dict encoding was its hot spot."""
        attrs = f', "attrs": {json.dumps(rec[6])}' if rec[6] else ""
        parent = f'"{rec[2]}"' if rec[2] is not None else "null"
        return (f'{{"trace_id": "{rec[0]}", "span_id": "{rec[1]}", '
                f'"parent_id": {parent}, "name": "{rec[3]}", '
                f'"service": "{self.service}", "ts": {rec[4]:.6f}, '
                f'"dur_ms": {rec[5] * 1e3:.4f}{attrs}}}' "\n")

    # -- spool (background drainer: serialization never taxes the
    # -- request path, and never runs under the ring lock) -------------
    def _start_drainer_locked(self) -> None:
        self._drain_stop.clear()
        self._drainer = threading.Thread(target=self._drain_loop,
                                         name="cos-trace-spool",
                                         daemon=True)
        self._drainer.start()

    def _drain_loop(self) -> None:
        # short cadence on purpose: draining is O(records since last
        # drain) of GIL-holding string work, and one big burst every
        # few seconds would stall the serving executor for its whole
        # duration — many small steals beat one long monopoly
        while not self._drain_stop.wait(0.2):
            self._drain_once()

    def _drain_once(self) -> None:
        self._absorb_staged()
        with self._spool_lock:
            with self._lock:
                batch, self._pending = self._pending, []
            if not batch or not self.spool_dir:
                return
            try:
                if self._spool is None:
                    os.makedirs(self.spool_dir, exist_ok=True)
                    self._spool_path = os.path.join(
                        self.spool_dir, f"trace-{os.getpid()}.jsonl")
                    self._spool = open(self._spool_path, "a")
                self._spool.write("".join(self._rec_to_line(r)
                                          for r in batch))
                self._spool.flush()
            except OSError as e:
                _LOG.warning("trace spool write failed (%s) — "
                             "disabling the spool, ring stays live",
                             e)
                self.spool_dir = ""
                self._spool = None

    def flush_spool(self) -> Optional[str]:
        """Force-drain pending records to the JSONL file (shutdown
        paths call this so a SIGTERM never loses the buffered tail)."""
        self._drain_once()
        return self._spool_path

    def recent(self, trace_id: Optional[str] = None,
               limit: int = 1024, min_ms: float = 0.0) -> List[dict]:
        """Finished spans, oldest first (ring order), optionally
        filtered to one trace and/or to spans at least `min_ms` long
        (the slow-exemplar query: pull one incident's spans without
        downloading the whole ring)."""
        self._absorb_staged()
        with self._lock:
            spans = list(self._ring)
        if trace_id:
            spans = [r for r in spans if r[0] == trace_id]
        if min_ms > 0:
            spans = [r for r in spans if r[5] * 1e3 >= min_ms]
        return [self._rec_to_dict(r) for r in spans[-limit:]]

    def reconfigure(self, sample: Optional[float] = None,
                    spool_dir: Optional[str] = None) -> "Tracer":
        """Benches/tests flip sampling inside one process; production
        sets COS_TRACE_SAMPLE before start and never calls this."""
        if sample is not None:
            self.sample = max(0.0, min(1.0, float(sample)))
        if spool_dir is not None:
            self._drain_once()          # land the old spool's tail
            with self._spool_lock:
                if self._spool is not None:
                    try:
                        self._spool.close()
                    except OSError:
                        pass
                self._spool = None
                self._spool_path = None
                with self._lock:
                    self._pending = []
                self.spool_dir = spool_dir
        return self


class _Activation:
    __slots__ = ("tracer", "ctx")

    def __init__(self, tracer: Tracer, ctx: SpanCtx):
        self.tracer = tracer
        self.ctx = ctx

    def __enter__(self):
        self.tracer._push(self.ctx)
        return self

    def __exit__(self, *exc):
        self.tracer._pop()
        return False


# -- process singleton --------------------------------------------------
_tracer: Optional[Tracer] = None
_tracer_lock = threading.Lock()


def get_tracer(service: str = "") -> Tracer:
    """The process tracer (created on first use; `service` names it on
    that first call — router vs replica vs trainer)."""
    global _tracer
    if _tracer is None:
        with _tracer_lock:
            if _tracer is None:
                _tracer = Tracer(service)
    return _tracer


def span_tree(spans: List[dict]) -> Dict[str, List[dict]]:
    """children-by-parent-id index (tests and the aggregate view)."""
    tree: Dict[str, List[dict]] = {}
    for s in spans:
        tree.setdefault(s.get("parent_id") or "", []).append(s)
    return tree
