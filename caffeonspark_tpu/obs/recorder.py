"""Flight recorder: a bounded in-memory ring of structured events,
dumped to an artifact when the process dies messily.

The metrics JSON says HOW MUCH (counters, percentiles); the recorder
says WHAT HAPPENED, IN WHAT ORDER: replica state transitions, drains,
rolling reloads, LRU evictions/page-ins, restarts, chaos faults,
snapshots, sync re-admissions, deploy verdicts.  When a
kill-under-load drill (or a real outage) ends a process, the ring is
the reconstructable timeline — "what did this process see in its
last N events" — instead of whatever half a log line made it to disk.

  * Recording is always-on and cheap: one lock + one list slot per
    event, at OPERATOR-EVENT rates (state changes, not requests).
    `COS_RECORDER_EVENTS` sizes the ring (default 512; 0 disables).
  * `COS_RECORDER_DUMP` names where the artifact lands: a `.json`
    path is used as-is; anything else is treated as a directory and
    each process writes `recorder-<pid>.json` inside it (fleet
    replicas inherit the env — per-pid names keep them from
    clobbering each other).
  * `maybe_dump(reason)` writes the artifact through the fsync'd
    atomic-write path; the serve/train SIGTERM handlers, fatal
    exception paths, and the chaos fault latch all call it, so a
    SIGKILL is the only death that loses the ring.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time
from collections import deque
from typing import List, Optional

from ..utils.envutils import env_int

_LOG = logging.getLogger(__name__)


class FlightRecorder:
    """LOCK-FREE by design: record() is called from signal handlers
    (the SIGTERM dump path records the signal itself), which run on
    the main thread between bytecodes — a mutex here would deadlock
    the process the moment a signal lands while the main thread holds
    it mid-record.  A bounded deque's append is a single GIL-atomic
    operation, so the handler can always record and the ring stays
    consistent without any lock."""

    def __init__(self, capacity: Optional[int] = None):
        cap = (capacity if capacity is not None
               else env_int("COS_RECORDER_EVENTS", 512, strict=False))
        self.capacity = max(0, cap)
        self._ring: "deque[dict]" = deque(maxlen=self.capacity or 1)
        self._seq = itertools.count(1)
        self._t0 = time.monotonic()

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    def record(self, source: str, event: str, **detail) -> None:
        """One structured event; `detail` values must be
        JSON-serializable (callers pass strings/numbers)."""
        if not self.capacity:
            return
        rec = {"seq": next(self._seq),
               "ts": round(time.time(), 6),
               "t_rel_s": round(time.monotonic() - self._t0, 6),
               "source": source, "event": event}
        if detail:
            rec.update(detail)
        self._ring.append(rec)

    def events(self) -> List[dict]:
        """Chronological snapshot of the ring."""
        return list(self._ring)

    def dump(self, path: str, reason: str = "") -> str:
        """Write the artifact via the fsync'd atomic-write path, so a
        crash racing the dump never leaves a truncated timeline."""
        from ..utils.fsutils import atomic_write_local
        events = self.events()
        doc = {"schema": "cos-flight-recorder-v1",
               "pid": os.getpid(),
               "dumped_at": round(time.time(), 6),
               "reason": reason,
               "dropped": max(0, (events[-1]["seq"] - len(events))
                              if events else 0),
               "events": events}

        def _write(tmp):
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=2, sort_keys=False)
                f.write("\n")

        atomic_write_local(path, _write)
        return path


# -- process singleton + dump plumbing ----------------------------------
_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def get_recorder() -> FlightRecorder:
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = FlightRecorder()
    return _recorder


def record(source: str, event: str, **detail) -> None:
    """Module-level convenience: every subsystem records through this
    one call, so the event stream interleaves in one ring."""
    get_recorder().record(source, event, **detail)


def dump_path() -> Optional[str]:
    """Resolved COS_RECORDER_DUMP target for THIS process, or None."""
    p = os.environ.get("COS_RECORDER_DUMP", "")
    if not p:
        return None
    if p.endswith(".json"):
        return p
    return os.path.join(p, f"recorder-{os.getpid()}.json")


def maybe_dump(reason: str) -> Optional[str]:
    """Dump the ring to the COS_RECORDER_DUMP target (no-op when the
    knob is unset or the recorder is disabled).  Never raises: this
    runs inside signal handlers and fatal-error paths, where a dump
    failure must not mask the real problem."""
    path = dump_path()
    rec = get_recorder()
    if path is None or not rec.enabled:
        return None
    try:
        rec.record("recorder", "dump", reason=reason)
        return rec.dump(path, reason=reason)
    except Exception as e:          # noqa: BLE001 — best-effort
        _LOG.warning("flight-recorder dump to %s failed: %s", path, e)
        return None


def load_dump_dir(path: str) -> List[dict]:
    """Merge every `recorder-*.json` dump under `path` (the per-pid
    artifacts a COS_RECORDER_DUMP directory accumulates across a
    fleet) into ONE causally-ordered timeline: events sorted by wall
    timestamp, ties broken by (pid, seq) so one process's own order
    is never shuffled.  Each event gains a `pid` field naming the
    process it came from — what incident reconstruction (prodday)
    walks to explain injected faults.  Unreadable/truncated dumps are
    skipped (a SIGKILL racing a dump must not sink the whole
    reconstruction)."""
    merged: List[dict] = []
    try:
        names = sorted(os.listdir(path))
    except OSError:
        return merged
    for name in names:
        if not (name.startswith("recorder-")
                and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(path, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if doc.get("schema") != "cos-flight-recorder-v1":
            continue
        pid = doc.get("pid")
        for ev in doc.get("events") or []:
            merged.append(dict(ev, pid=pid))
    merged.sort(key=lambda e: (e.get("ts", 0.0), e.get("pid") or 0,
                               e.get("seq", 0)))
    return merged
