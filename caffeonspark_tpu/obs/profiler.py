"""On-demand profiler capture: a bounded `jax.profiler.trace` on a
LIVE process, started over HTTP instead of a restart with `-profile`.

`POST /v1/profile {"duration_ms": N}` on a serving replica (or the
training-side metrics port) captures N ms of XLA device timeline into
a TensorBoard-loadable trace directory and answers with its path —
concurrent requests keep serving; the profiler rides alongside.

One capture at a time per process (jax.profiler is a process-global),
enforced with a non-blocking try-lock: a second POST while one runs
answers 409 instead of queueing operator requests behind each other.
Duration is clamped to PROFILE_MAX_MS so a fat-fingered request can't
leave the profiler running for an hour.
"""

from __future__ import annotations

import logging
import os
import tempfile
import threading
import time

_LOG = logging.getLogger(__name__)

PROFILE_DEFAULT_MS = 1000.0
PROFILE_MAX_MS = 30_000.0

_capture_lock = threading.Lock()


class ProfilerBusy(RuntimeError):
    """A capture is already running in this process (HTTP 409)."""


def capture(duration_ms: float = PROFILE_DEFAULT_MS,
            log_dir: str = "") -> dict:
    """Run one bounded jax.profiler trace; returns
    {"trace_dir", "duration_ms"}.  The sleep bounds the capture —
    device work proceeds normally underneath it (the profiler hooks
    the runtime, it does not serialize it)."""
    dur = max(10.0, min(float(duration_ms or PROFILE_DEFAULT_MS),
                        PROFILE_MAX_MS))
    if not _capture_lock.acquire(blocking=False):
        raise ProfilerBusy("a profiler capture is already running")
    try:
        out_dir = log_dir or os.environ.get("COS_PROFILE_DIR", "")
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
            trace_dir = tempfile.mkdtemp(prefix="cos_profile_",
                                         dir=out_dir)
        else:
            trace_dir = tempfile.mkdtemp(prefix="cos_profile_")
        import jax
        t0 = time.monotonic()
        jax.profiler.start_trace(trace_dir)
        try:
            time.sleep(dur / 1e3)
        finally:
            jax.profiler.stop_trace()
        wall = time.monotonic() - t0
        _LOG.info("profiler capture: %.0f ms -> %s", wall * 1e3,
                  trace_dir)
        return {"trace_dir": trace_dir,
                "duration_ms": round(wall * 1e3, 1)}
    finally:
        _capture_lock.release()
