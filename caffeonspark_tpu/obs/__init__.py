"""End-to-end observability layer (ISSUE 15).

The one package every subsystem reports through:

  * `trace`    — distributed request tracing: `X-COS-Trace` ids minted
                 at the client/router, spans for router pick/retry,
                 replica queue-wait, flush assembly, padding, device
                 execution; sampled via COS_TRACE_SAMPLE (0 = inert),
                 spooled as per-process JSONL under COS_TRACE_DIR, and
                 aggregated cross-replica by the router.
  * `recorder` — flight recorder: bounded in-memory ring of structured
                 events (state transitions, drains, reloads,
                 evictions, chaos faults, verdicts), dumped to the
                 COS_RECORDER_DUMP artifact on SIGTERM / fatal
                 exception / fault latch.
  * `prom`     — Prometheus exposition of the PipelineMetrics summary
                 (`/metrics?format=prom` on replica, router, and the
                 training metrics port), plus the round-trip validator.
  * `profiler` — on-demand bounded `jax.profiler` capture
                 (`POST /v1/profile`) on a live process.
  * `http`     — the training-side metrics port (COS_METRICS_PORT).

Everything here is HOST-side plumbing: nothing imports jax at module
scope, nothing runs at trace time, and every knob resolves once per
process (coslint COS003 discipline).
"""

from .recorder import (FlightRecorder, dump_path, get_recorder,
                       maybe_dump, record)
from .trace import (NULL_SPAN, TRACE_HEADER, Span, SpanCtx, Tracer,
                    get_tracer, parse_header, span_tree)
from .prom import (PromWriter, counter_values, parse_exposition,
                   render_summary)
from .profiler import ProfilerBusy, capture
from .http import ObsHTTPServer, maybe_start_obs_server

__all__ = [
    "FlightRecorder", "dump_path", "get_recorder", "maybe_dump",
    "record", "NULL_SPAN", "TRACE_HEADER", "Span", "SpanCtx",
    "Tracer", "get_tracer", "parse_header", "span_tree",
    "PromWriter", "counter_values", "parse_exposition",
    "render_summary", "ProfilerBusy", "capture", "ObsHTTPServer",
    "maybe_start_obs_server",
]
