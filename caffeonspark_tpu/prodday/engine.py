"""Production-day engine: run a scenario against a live stack and
emit the day's verdict document.

One compressed day = scenario phases driven in order:

  * an open-loop TrafficGen replays each phase's load shape against
    the router (zipfian payload mix, malformed injection, tenant
    classes → per-model FlushLanes);
  * a fault scheduler fires each phase's chaos at its at_s on the
    compressed clock, through the EXISTING runtime hooks only —
    `Fleet.kill_replica`, POST /v1/faults (set_replica_fault),
    `DeployController.refresh_faults(env)` — never by reaching into
    internals (the drill must exercise the same levers an operator
    has);
  * a PromScraper samples the router's fleet-aggregated exposition
    on the scrape interval — the verdict engine sees the day only
    through those scrapes plus the flight-recorder dumps, exactly
    the operator's view.

End of day: stop the stack (SIGTERM → every replica's recorder dump
lands in COS_RECORDER_DUMP), dump the harness's own ring, merge, and
judge — per-phase SLO/error budgets, incident reconstruction (every
injected fault explained), slow-trace exemplars, leak gates against
the pre-start snapshot.

Knobs (resolved once, constructor time — COS003):

  COS_PRODDAY_SCRAPE_S    scrape interval override (default: the
                          scenario's scrape_interval_s)
  COS_PRODDAY_RECOVERY_S  deadline for a fault's recovery event in
                          the merged timeline (default 60)
  COS_PRODDAY_EXEMPLARS   slowest-request traces kept (default 3)
  COS_PRODDAY_INFLIGHT    traffic generator in-flight cap (default 64)
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Dict, List, Optional

from ..obs import recorder
from ..obs.trace import SpanCtx
from ..tools import chaos
from ..utils.envutils import env_int, env_num
from .leaks import leak_gates, snapshot_leaks
from .scenario import Fault, Scenario, Tenant
from .traffic import TrafficGen, summarize
from .verdict import (PromScraper, detect_restarts, error_budget,
                      reconstruct_incidents, slow_exemplars)


class FleetStack:
    """The engine's view of the system under test: a DeployController
    (full PR 13 loop — streaming ingest → fine-tune → canary → fleet)
    or a bare Fleet, behind the handful of operator-shaped verbs the
    scenario kinds map onto."""

    def __init__(self, controller=None, fleet=None):
        if controller is None and fleet is None:
            raise ValueError("FleetStack needs a controller or fleet")
        self.controller = controller
        self.fleet = fleet
        self.autoscaler = None
        self._round_lock = threading.Lock()

    # -- lifecycle ----------------------------------------------------
    def start(self) -> "FleetStack":
        if self.controller is not None:
            if self.controller.fleet is None:
                self.controller.start()
            self.fleet = self.controller.fleet
        elif not self.fleet.replicas:
            self.fleet.start()
        # COS_AS_ENABLE=1 closes the control loop for the day: the
        # autoscaler reads the router's scrape signals and drives the
        # Fleet scale verbs (knobs resolve inside the controller)
        from ..serving.autoscale import AutoScaler
        self.autoscaler = AutoScaler.from_env(self.fleet)
        if self.autoscaler is not None:
            self.autoscaler.start()
        return self

    def stop(self) -> None:
        if self.autoscaler is not None:
            self.autoscaler.stop()
            self.autoscaler = None
        if self.controller is not None:
            self.controller.stop()
            self.fleet = None
        elif self.fleet is not None:
            self.fleet.stop()

    # -- traffic ------------------------------------------------------
    def predict(self, payload: bytes, tenant: Tenant,
                trace_id: Optional[str]) -> int:
        """One client request through the router; returns the HTTP
        status the CLIENT saw (router retries/hedges are invisible
        here, as they are to a real client).  A caller-chosen trace
        id rides in as the parent ctx so the request's attempt spans
        land under an id the harness can query back."""
        from ..serving.router import RouterRequestError
        parts = []
        if tenant.model:
            parts.append(f"model={tenant.model}")
        # admission-class routing rides the query string; replicas
        # without COS_LANES simply ignore both params
        if getattr(tenant, "lane", None):
            parts.append(f"lane={tenant.lane}")
            parts.append(f"tenant={tenant.name}")
        query = "&".join(parts)
        trace = SpanCtx(trace_id, "0" * 16) if trace_id else None
        try:
            self.fleet.router.predict(payload, query=query,
                                      trace=trace)
            return 200
        except RouterRequestError as e:
            return e.code

    # -- observability ------------------------------------------------
    def scrape(self) -> str:
        return self.fleet.router.prom_summary()

    def collect_traces(self, trace_id: str) -> List[dict]:
        return self.fleet.router.collect_traces(trace_id, min_ms=0.0)

    def residency(self) -> Dict[str, List[str]]:
        out: Dict[str, List[str]] = {}
        for model, st in self.fleet.router.models_summary().items():
            if isinstance(st, dict):
                out[model] = list(st.get("resident_on") or [])
        return out

    # -- chaos verbs --------------------------------------------------
    def kill_replica(self, index: int) -> None:
        self.fleet.kill_replica(f"replica{index}")

    def set_replica_fault(self, index: int,
                          env: Dict[str, Optional[str]]) -> None:
        self.fleet.set_replica_fault(f"replica{index}", env)

    def refresh_faults(self, env: Dict[str, Optional[str]]) -> None:
        if self.controller is not None:
            self.controller.refresh_faults(env)
        else:
            chaos.apply_fault_env(env)

    def settle(self, timeout_s: float = 30.0) -> bool:
        """Wait until every replica is alive and routable (state=ok)
        — end-of-day runs this so a kill near the day's end still
        gets its respawn (and the scraper still gets the new pid's
        build_info, which is what explains the counter reset)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            reps = self.fleet.router.metrics_summary()["replicas"]
            if reps and all(r.get("state") == "ok"
                            for r in reps.values()) \
                    and all(rep.alive()
                            for rep in self.fleet.replicas.values()):
                return True
            time.sleep(0.25)
        return False

    def run_round(self) -> dict:
        """One full deploy round; serialized — the controller's round
        loop is single-operator by design, and two scheduled faults
        both wanting 'the next round' must take turns."""
        if self.controller is None:
            raise RuntimeError("scenario schedules a deploy round "
                               "but the stack has no controller")
        with self._round_lock:
            return self.controller.run_round()


class ProdDay:
    """Run one scenario; `run()` returns the verdict document."""

    def __init__(self, scenario: Scenario, stack: FleetStack, *,
                 payload_pool: List[bytes],
                 malformed_pool: Optional[List[bytes]] = None,
                 dump_dir: Optional[str] = None):
        self.scenario = scenario
        self.stack = stack
        self.payload_pool = payload_pool
        self.malformed_pool = malformed_pool or []
        self.dump_dir = dump_dir
        self.scrape_s = env_num("COS_PRODDAY_SCRAPE_S",
                                scenario.scrape_interval_s,
                                strict=False)
        self.recovery_s = env_num("COS_PRODDAY_RECOVERY_S", 60.0,
                                  strict=False)
        self.exemplars_n = env_int("COS_PRODDAY_EXEMPLARS", 3,
                                   strict=False)
        self.inflight_cap = env_int("COS_PRODDAY_INFLIGHT", 64,
                                    strict=False)
        self.injected: List[dict] = []
        self.fault_errors: List[str] = []
        self._inj_lock = threading.Lock()
        # one-shot chaos knobs (canary kill, snapshot truncate,
        # reload fail) latch on marker FILES — each firing gets its
        # own, so two scheduled faults of one kind both fire
        self._work = tempfile.mkdtemp(prefix="cos_prodday_")

    # -- fault firing -------------------------------------------------
    def _record_injection(self, fault: Fault, phase: str,
                          error: Optional[str] = None) -> None:
        rec = dict(fault.to_dict(), phase=phase,
                   t_wall=time.time())
        if error:
            rec["error"] = error
            self.fault_errors.append(
                f"{phase}/{fault.kind}@{fault.at_s:g}s: {error}")
        with self._inj_lock:
            self.injected.append(rec)

    def _fire(self, fault: Fault, phase: str,
              stop: threading.Event) -> None:
        """One scheduled fault's whole lifecycle in its own thread:
        wait for at_s, fire through the operator hook, and (stateful
        kinds) wait again and clear at clear_at_s.  The injection is
        recorded even when the hook errors — an injector that
        silently did nothing must FAIL reconstruction, not vanish
        from it."""
        recorded = [False]

        def note(error=None):
            recorded[0] = True
            self._record_injection(fault, phase, error=error)

        try:
            if fault.kind == "replica_kill":
                note()
                self.stack.kill_replica(fault.replica)
            elif fault.kind == "replica_slow":
                knob = {"COS_FAULT_REPLICA_SLOW":
                        f"{fault.replica}:{fault.factor:g}"}
                note()
                self.stack.set_replica_fault(fault.replica, knob)
                if fault.clear_at_s is not None:
                    stop.wait(fault.clear_at_s - fault.at_s)
                    self.stack.set_replica_fault(
                        fault.replica, {"COS_FAULT_REPLICA_SLOW":
                                        None})
            elif fault.kind == "flaky_storage":
                note()
                self.stack.refresh_faults(
                    {"COS_FAULT_FLAKY_STORAGE": f"{fault.p:g}"})
                if fault.clear_at_s is not None:
                    stop.wait(fault.clear_at_s - fault.at_s)
                    self.stack.refresh_faults(
                        {"COS_FAULT_FLAKY_STORAGE": None})
            elif fault.kind in ("snapshot_truncate", "canary_kill",
                                "reload_fail"):
                # deploy-loop faults: arm the knob, run the round the
                # fault manifests in, then disarm — the same flip/
                # round/flip sequence the deploy drills use
                marker = os.path.join(
                    self._work,
                    f"{phase}-{fault.kind}-{fault.at_s:g}.marker")
                knob = {
                    "snapshot_truncate":
                        {"COS_FAULT_SNAPSHOT_TRUNCATE": marker},
                    "canary_kill":
                        {"COS_FAULT_CANARY_KILL":
                         f"{fault.after_requests}:{marker}"},
                    "reload_fail":
                        {"COS_FAULT_RELOAD_FAIL_RANK":
                         f"{fault.replica}:{marker}"},
                }[fault.kind]
                self.stack.refresh_faults(knob)
                note()
                try:
                    self.stack.run_round()
                finally:
                    self.stack.refresh_faults(
                        {k: None for k in knob})
            elif fault.kind == "deploy_round":
                # an ACTION, not a fault: no injection record, no
                # reconstruction obligation
                self.stack.run_round()
        except Exception as e:       # noqa: BLE001 — surfaced in doc
            if fault.kind == "deploy_round" or recorded[0]:
                self.fault_errors.append(
                    f"{phase}/{fault.kind}@{fault.at_s:g}s: {e}")
            else:
                note(error=str(e))

    def _schedule_phase_faults(self, phase, stop: threading.Event
                               ) -> List[threading.Thread]:
        threads = []
        for fault in phase.faults:
            def run(f=fault):
                if not stop.wait(f.at_s):
                    self._fire(f, phase.name, stop)
            th = threading.Thread(
                target=run, daemon=True,
                name=f"cos-prodday-fault-{phase.name}-{fault.kind}")
            th.start()
            threads.append(th)
        return threads

    # -- the day ------------------------------------------------------
    def run(self) -> dict:
        sc = self.scenario
        start_snap = snapshot_leaks()
        self.stack.start()
        start_snap["resident_pairs"] = snapshot_leaks(
            self.stack.residency())["resident_pairs"]
        scraper = PromScraper(self.stack.scrape,
                              interval_s=self.scrape_s).start()
        gen = TrafficGen(self.stack.predict, self.payload_pool,
                         self.malformed_pool, seed=sc.seed,
                         inflight_cap=self.inflight_cap)
        recorder.record("prodday", "day_start",
                              scenario=sc.name)
        fault_stop = threading.Event()
        fault_threads: List[threading.Thread] = []
        phase_runs = []              # (phase, t0, t1, results)
        for phase in sc.phases:
            recorder.record("prodday", "phase_start",
                                  phase=phase.name)
            fault_threads += self._schedule_phase_faults(phase,
                                                         fault_stop)
            t0 = time.monotonic()
            results = gen.run_phase(phase.load, phase.duration_s)
            phase_runs.append((phase, t0, time.monotonic(), results))
        # let in-flight deploy rounds land before judging (they carry
        # the recovery events reconstruction is owed), then release
        # any still-armed clear timers
        for th in fault_threads:
            th.join(timeout=180.0)
        fault_stop.set()
        stragglers = [th.name for th in fault_threads
                      if th.is_alive()]
        # recovery settle BEFORE the scraper stops: a kill near the
        # day's end needs its respawn scraped (new pid in
        # cos_build_info) for the counter reset to be explained
        settled = self.stack.settle()
        scraper.stop()
        recorder.record("prodday", "day_end", scenario=sc.name)

        all_results = [r for _, _, _, rs in phase_runs for r in rs]
        exemplars = slow_exemplars(all_results,
                                   self.stack.collect_traces,
                                   n=self.exemplars_n)
        residency_end = self.stack.residency()
        self.stack.stop()            # SIGTERM → replica dumps land
        recorder.maybe_dump("prodday_end")
        end_snap = snapshot_leaks()
        end_snap["resident_pairs"] = snapshot_leaks(
            residency_end)["resident_pairs"]
        leaks = leak_gates(start_snap, end_snap)

        timeline = (recorder.load_dump_dir(self.dump_dir)
                    if self.dump_dir else
                    recorder.get_recorder().events())
        reconstruction = reconstruct_incidents(
            timeline, self.injected,
            recovery_deadline_s=self.recovery_s)

        restarts = detect_restarts(scraper.samples)
        phase_docs = []
        for phase, t0, t1, results in phase_runs:
            traffic = summarize(results)
            budget = error_budget(scraper.samples, t0, t1, phase.slo,
                                  restarts=restarts)
            phase_docs.append({
                "name": phase.name,
                "duration_s": phase.duration_s,
                "traffic": traffic,
                "budget": budget,
                "ok": bool(budget["slo_ok"]
                           and traffic["malformed_mishandled"] == 0),
            })
        doc = {
            "scenario": {"name": sc.name, "seed": sc.seed,
                         "duration_s": sc.duration_s,
                         "phases": len(sc.phases)},
            "phases": phase_docs,
            "incidents": reconstruction,
            "leaks": leaks,
            "exemplars": exemplars,
            "restarts_detected": restarts,
            "settled": settled,
            "scrape_samples": len(scraper.samples),
            "scrape_parse_errors": scraper.parse_errors,
            "fault_errors": self.fault_errors,
            "fault_stragglers": stragglers,
        }
        doc["gates"] = {
            "slo": all(p["ok"] for p in phase_docs),
            "incidents_explained": reconstruction["ok"],
            "leaks": bool(leaks["ok"]),
            "scrapes_clean": not scraper.parse_errors,
            "faults_clean": not self.fault_errors
            and not stragglers,
        }
        doc["ok"] = all(doc["gates"].values())
        return doc
