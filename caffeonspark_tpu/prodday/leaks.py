"""End-of-day leak gates: what a compressed production day must NOT
accumulate.

A day of chaos (replica kills, respawns, deploy rounds, canary
subprocesses) exercises every create/destroy path in the repo; the
leak gates compare a start-of-day snapshot against end-of-day and
fail the run when something survived that shouldn't have:

  fds        open file descriptors of the harness process
             (/proc/self/fd) — a leaked socket or spool handle per
             round compounds into EMFILE on a real day
  children   live child processes (walk /proc for ppid == us) — a
             replica or canary the teardown failed to reap
  threads    named live threads — a poller/monitor thread that
             outlived its stop()
  residency  HBM/registry residency: (model, replica) resident pairs
             reported by the serving stack — a paged-in model nothing
             references any more

Each gate carries a small tolerance (allowlist + slack) because the
process model has legitimate lazily-created singletons (the trace
spool drainer thread, the recorder); the gates are calibrated so a
PLANTED leak of each class trips its gate (pinned by tests).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

# threads the process legitimately creates lazily and never joins
# (module singletons); a leak gate must not flag the first drill that
# happened to touch tracing
THREAD_ALLOWLIST = ("cos-trace-spool", "cos-metrics-flusher",
                    "pydevd", "MainThread")


def open_fds() -> Optional[List[str]]:
    """Open fd numbers of this process (None when /proc is absent —
    the gate then reports 'skipped' instead of guessing)."""
    try:
        return sorted(os.listdir("/proc/self/fd"))
    except OSError:
        return None


def child_pids() -> Optional[List[int]]:
    """Live direct children of this process via /proc/*/stat ppid
    (field 4 — after the parenthesized comm, which may itself contain
    spaces, so parse from the LAST ')')."""
    me = os.getpid()
    out: List[int] = []
    try:
        entries = os.listdir("/proc")
    except OSError:
        return None
    for name in entries:
        if not name.isdigit():
            continue
        try:
            with open(f"/proc/{name}/stat") as f:
                stat = f.read()
            rest = stat[stat.rfind(")") + 2:].split()
            state, ppid = rest[0], int(rest[1])
        except (OSError, ValueError, IndexError):
            continue
        if ppid == me and state != "Z":     # reaped zombies don't count
            out.append(int(name))
    return sorted(out)


def thread_names() -> List[str]:
    return sorted(t.name for t in threading.enumerate() if t.is_alive())


def snapshot_leaks(residency: Optional[Dict[str, List[str]]] = None
                   ) -> dict:
    """One comparable snapshot.  `residency` is the serving stack's
    {model: [replica, ...]} resident map (engine supplies it from
    router /v1/models; None = not applicable)."""
    resident_pairs = sorted(
        f"{m}@{r}" for m, reps in (residency or {}).items()
        for r in reps)
    return {"fds": open_fds(), "children": child_pids(),
            "threads": thread_names(),
            "resident_pairs": resident_pairs}


def _gate(ok: Optional[bool], detail: dict) -> dict:
    out = {"ok": ok, **detail}
    if ok is None:
        out["skipped"] = True
    return out


def leak_gates(start: dict, end: dict, *, fd_slack: int = 2,
               thread_allow: tuple = THREAD_ALLOWLIST,
               residency_slack: int = 0) -> dict:
    """Compare two snapshots; returns per-gate verdicts + overall.

    fds: end count may exceed start by at most `fd_slack` (lazily
    opened singletons like the trace spool file are real and fine;
    a per-round leak is not).  children: every end-of-day child must
    have existed at start (no tolerance — the harness owns its
    process tree).  threads: any non-allowlisted thread present at
    end but not at start fails.  residency: at most
    `residency_slack` new (model, replica) resident pairs."""
    gates: Dict[str, dict] = {}

    if start.get("fds") is None or end.get("fds") is None:
        gates["fds"] = _gate(None, {})
    else:
        n0, n1 = len(start["fds"]), len(end["fds"])
        gates["fds"] = _gate(n1 <= n0 + fd_slack,
                             {"start": n0, "end": n1,
                              "slack": fd_slack})

    if start.get("children") is None or end.get("children") is None:
        gates["children"] = _gate(None, {})
    else:
        new = sorted(set(end["children"]) - set(start["children"]))
        gates["children"] = _gate(not new,
                                  {"start": len(start["children"]),
                                   "end": len(end["children"]),
                                   "leaked_pids": new})

    new_threads = sorted(
        t for t in set(end.get("threads") or [])
        - set(start.get("threads") or [])
        if not any(t.startswith(a) for a in thread_allow))
    gates["threads"] = _gate(not new_threads,
                             {"start": len(start.get("threads") or []),
                              "end": len(end.get("threads") or []),
                              "leaked": new_threads})

    p0 = set(start.get("resident_pairs") or [])
    p1 = set(end.get("resident_pairs") or [])
    new_pairs = sorted(p1 - p0)
    gates["residency"] = _gate(len(new_pairs) <= residency_slack,
                               {"start": sorted(p0), "end": sorted(p1),
                                "leaked": new_pairs,
                                "slack": residency_slack})

    gates["ok"] = all(g["ok"] is not False for g in gates.values()
                      if isinstance(g, dict))
    return gates
