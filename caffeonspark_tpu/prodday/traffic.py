"""Open-loop traffic generator for the production-day harness.

OPEN-loop is the point: a closed-loop client (send, wait, send) slows
down exactly when the system does, hiding the queueing collapse that
"RPC Considered Harmful" shows dominates small-payload serving.  Here
arrivals follow a Poisson process whose rate tracks the phase's load
shape regardless of completions — when the fleet falls behind, work
piles up the way a real flash crowd piles up.  (A bounded in-flight
cap protects the host box; requests shed at the cap are COUNTED as
offered-but-shed, never silently dropped.)

Load shapes over a phase of duration T (t in [0, T]):

  flat      r(t) = rps
  ramp      r(t) = rps * (floor + (1-floor) * t/T)
  diurnal   r(t) = rps * (floor + (1-floor) * ½(1-cos 2πt/T))
            — one day's trough→peak→trough in one phase
  flash     r(t) = rps, ×spike_x inside the window
            [spike_at*T, (spike_at+spike_frac)*T] — the flash crowd

Payload mix is zipfian over a pool of pre-serialized request bodies
(PR 16's cache premise: a hot head of repeated payloads is what makes
the content-hash response cache and in-flight coalescing pay), with
`malformed_p` of requests drawn from an adversarial pool — those must
come back 4xx, never 5xx, and never crash a replica.  Tenant classes
(interactive/batch/...) pick per-request by weight and may route to a
named model — mapping onto the service's per-model FlushLanes.
"""

from __future__ import annotations

import math
import random
import threading
import time
from typing import Callable, Dict, List, NamedTuple, Optional

from .scenario import LoadSpec, Tenant


class RequestResult(NamedTuple):
    t_rel_s: float           # send time relative to phase start
    lat_ms: float
    status: int              # HTTP-ish status (0 = transport failure)
    tenant: str
    malformed: bool
    shed: bool               # dropped at the local in-flight cap
    trace_id: Optional[str]


def rate_at(load: LoadSpec, t: float, duration_s: float) -> float:
    """Target arrival rate (req/s) at phase-relative time t."""
    frac = min(1.0, max(0.0, t / duration_s)) if duration_s else 0.0
    if load.shape == "flat":
        return load.rps
    if load.shape == "ramp":
        return load.rps * (load.floor + (1 - load.floor) * frac)
    if load.shape == "diurnal":
        return load.rps * (load.floor + (1 - load.floor)
                           * 0.5 * (1 - math.cos(2 * math.pi * frac)))
    if load.shape == "flash":
        lo, hi = load.spike_at, load.spike_at + load.spike_frac
        return load.rps * (load.spike_x if lo <= frac < hi else 1.0)
    raise ValueError(f"unknown shape {load.shape!r}")


def zipf_ranks(n: int, hot: int, rng: random.Random,
               s: float = 1.0) -> Callable[[], int]:
    """Sampler over [0, n): zipf-weighted ranks — rank 0 hottest.
    `hot` only shapes the head steepness indirectly via n; kept for
    symmetry with the scenario schema (pool/hot document intent)."""
    weights = [1.0 / (r + 1) ** s for r in range(n)]
    total = sum(weights)
    cdf, acc = [], 0.0
    for w in weights:
        acc += w / total
        cdf.append(acc)

    def pick() -> int:
        u = rng.random()
        for i, c in enumerate(cdf):
            if u <= c:
                return i
        return n - 1
    return pick


class TrafficGen:
    """Drive one phase's load against a `send` callable.

    send(payload: bytes, tenant: Tenant, trace_id: str|None) -> int
    returns an HTTP status (or raises — counted as transport failure,
    status 0).  Payload pools are pre-serialized bytes so the
    generator's own CPU cost stays flat across phases."""

    def __init__(self, send: Callable[[bytes, Tenant, Optional[str]],
                                      int],
                 payload_pool: List[bytes],
                 malformed_pool: Optional[List[bytes]] = None,
                 *, seed: int = 7, inflight_cap: int = 64,
                 workers: int = 16, trace_every: int = 1):
        if not payload_pool:
            raise ValueError("payload_pool must be non-empty")
        self.send = send
        self.payload_pool = list(payload_pool)
        self.malformed_pool = list(malformed_pool or [])
        self.seed = seed
        self.inflight_cap = inflight_cap
        self.workers = workers
        self.trace_every = max(1, trace_every)
        self._inflight = 0
        self._lock = threading.Lock()

    # -- one phase ----------------------------------------------------
    def run_phase(self, load: LoadSpec, duration_s: float,
                  stop: Optional[threading.Event] = None
                  ) -> List[RequestResult]:
        """Open-loop replay of one phase; blocks for ~duration_s and
        returns every offered request's outcome."""
        rng = random.Random(self.seed)
        pick_payload = zipf_ranks(
            min(load.zipf_pool, len(self.payload_pool)),
            load.zipf_hot, rng)
        tenants = load.tenants or [Tenant("default", 1.0)]
        t_weights = [t.weight for t in tenants]
        results: List[RequestResult] = []
        res_lock = threading.Lock()
        threads: List[threading.Thread] = []
        t0 = time.monotonic()
        seq = 0
        t_next = 0.0
        while True:
            now = time.monotonic() - t0
            if now >= duration_s or (stop is not None
                                     and stop.is_set()):
                break
            if t_next > now:
                time.sleep(min(t_next - now, 0.05))
                continue
            # fire the arrival scheduled for t_next
            seq += 1
            tenant = rng.choices(tenants, weights=t_weights)[0]
            malformed = (self.malformed_pool
                         and rng.random() < load.malformed_p)
            payload = (rng.choice(self.malformed_pool) if malformed
                       else self.payload_pool[pick_payload()
                                              % len(self.payload_pool)])
            trace_id = (f"pd{self.seed:x}{seq:08x}"
                        if seq % self.trace_every == 0 else None)
            with self._lock:
                shed = self._inflight >= self.inflight_cap
                if not shed:
                    self._inflight += 1
            if shed:
                with res_lock:
                    results.append(RequestResult(
                        round(t_next, 4), 0.0, 0, tenant.name,
                        bool(malformed), True, None))
            else:
                th = threading.Thread(
                    target=self._fire,
                    args=(payload, tenant, trace_id, bool(malformed),
                          t_next, t0, results, res_lock),
                    daemon=True)
                th.start()
                threads.append(th)
            # open loop: next arrival from the CURRENT target rate,
            # independent of completions
            r = max(1e-6, rate_at(load, t_next, duration_s))
            t_next += rng.expovariate(r)
        for th in threads:
            th.join(timeout=30.0)
        results.sort(key=lambda r: r.t_rel_s)
        return results

    def _fire(self, payload, tenant, trace_id, malformed, t_sched,
              t0, results, res_lock):
        t_send = time.monotonic()
        try:
            status = self.send(payload, tenant, trace_id)
        except Exception:           # noqa: BLE001 — transport failure
            status = 0
        lat_ms = (time.monotonic() - t_send) * 1e3
        with self._lock:
            self._inflight -= 1
        with res_lock:
            results.append(RequestResult(
                round(t_sched, 4), round(lat_ms, 3), int(status),
                tenant.name, malformed, False, trace_id))


def summarize(results: List[RequestResult]) -> Dict[str, object]:
    """Client-side ground truth for one phase: counts by outcome
    class, latency percentiles of well-formed successes, per-tenant
    rollup, and the malformed-handling check (a malformed payload
    must 4xx, never 5xx/transport — adversarial inputs crashing a
    replica would show up here first)."""
    ok = [r for r in results if not r.malformed and not r.shed
          and 200 <= r.status < 300]
    lat = sorted(r.lat_ms for r in ok)

    def pct(p: float) -> Optional[float]:
        if not lat:
            return None
        return round(lat[min(len(lat) - 1,
                             int(p * (len(lat) - 1)))], 3)

    wellformed = [r for r in results if not r.malformed]
    failures = [r for r in wellformed if not r.shed
                and not 200 <= r.status < 300]
    malformed = [r for r in results if r.malformed and not r.shed]
    mal_bad = [r for r in malformed
               if r.status >= 500 or r.status == 0]
    tenants: Dict[str, Dict[str, int]] = {}
    for r in results:
        t = tenants.setdefault(r.tenant, {"offered": 0, "ok": 0,
                                          "failed": 0, "shed": 0})
        t["offered"] += 1
        if r.shed:
            t["shed"] += 1
        elif 200 <= r.status < 300:
            t["ok"] += 1
        else:
            t["failed"] += 1
    return {
        "offered": len(results),
        "ok": len(ok),
        "failed": len(failures),
        "shed": sum(1 for r in results if r.shed),
        "malformed_offered": len(malformed),
        "malformed_mishandled": len(mal_bad),
        "p50_ms": pct(0.50), "p95_ms": pct(0.95), "p99_ms": pct(0.99),
        "max_ms": round(lat[-1], 3) if lat else None,
        "tenants": tenants,
    }
