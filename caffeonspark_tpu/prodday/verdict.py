"""Verdict engine: pass/fail decided from the observability substrate.

The production-day harness never asks the system under test how it
feels — every verdict is computed from what an OPERATOR could see:

  * per-phase SLO compliance + error-budget accounting from periodic
    router prom scrapes (`parse_exposition` re-validates every scrape
    the way a real scraper would);
  * counter deltas between scrapes clamp at zero ONLY when a restart
    was detected for that process (cos_uptime_seconds decreased or
    the cos_build_info pid label changed) — an unexplained counter
    reset is itself a finding;
  * post-run incident reconstruction: flight-recorder dumps from
    every process merge into one causally-ordered timeline
    (obs.recorder.load_dump_dir) and every injected fault must be
    EXPLAINED — its evidence event must appear after injection and
    its recovery event within COS_PRODDAY_RECOVERY_S;
  * trace exemplars: the N slowest client requests' trace ids are
    fetched back through `/v1/traces?trace=&min_ms=` so the artifact
    carries the span decomposition of the day's worst latency.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..obs.prom import counter_values, parse_exposition


class PromScraper:
    """Periodic scrape loop: `scrape()` returns exposition text (the
    router's fleet-aggregated /metrics?format=prom); each sample is
    parsed (strict) and timestamped.  Parse failures are recorded,
    not swallowed — a scrape a real Prometheus would reject is a
    finding in itself."""

    def __init__(self, scrape: Callable[[], str],
                 interval_s: float = 0.5):
        self._scrape = scrape
        self.interval_s = interval_s
        self.samples: List[Tuple[float, Dict[str, dict]]] = []
        self.parse_errors: List[str] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "PromScraper":
        self._thread = threading.Thread(target=self._loop,
                                        name="cos-prodday-scraper",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    def scrape_once(self) -> None:
        t = time.monotonic()
        try:
            fams = parse_exposition(self._scrape())
        except Exception as e:       # noqa: BLE001 — recorded finding
            self.parse_errors.append(f"t={t:.3f}: "
                                     f"{type(e).__name__}: {e}")
            return
        self.samples.append((t, fams))

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.scrape_once()
            self._stop.wait(self.interval_s)
        self.scrape_once()           # one last sample closes the day


# ---------------------------------------------------------------------------
# restart detection + budget math
# ---------------------------------------------------------------------------

def _identity_keys(fams: Dict[str, dict]) -> Dict[str, str]:
    """{process-label-set: pid} from cos_build_info samples — the
    restart detector's identity map."""
    out: Dict[str, str] = {}
    for labels, _v in (fams.get("cos_build_info") or
                       {"samples": []})["samples"]:
        ident = ",".join(f"{k}={v}" for k, v in sorted(labels.items())
                         if k not in ("pid",))
        out[ident] = labels.get("pid", "")
    return out


def _uptimes(fams: Dict[str, dict]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for labels, v in (fams.get("cos_uptime_seconds") or
                      {"samples": []})["samples"]:
        key = ",".join(f"{k}={v2}"
                       for k, v2 in sorted(labels.items()))
        out[key] = v
    return out


def detect_restarts(samples: List[Tuple[float, Dict[str, dict]]]
                    ) -> List[dict]:
    """Scan the scrape series for process restarts: a cos_build_info
    pid change or a cos_uptime_seconds decrease for the same label
    set.  Identity carries forward across scrape GAPS — a killed
    replica disappears from the fleet scrape while it is down, so
    the old and new pid are never in adjacent samples; comparing
    against the last SEEN identity is what catches the respawn."""
    out: List[dict] = []
    last_pid: Dict[str, str] = {}
    last_up: Dict[str, float] = {}
    for t, fams in samples:
        for ident, pid in _identity_keys(fams).items():
            old = last_pid.get(ident)
            if old is not None and old != pid:
                out.append({"who": ident, "kind": "pid_change",
                            "t": round(t, 3),
                            "old_pid": old, "new_pid": pid})
            last_pid[ident] = pid
        for key, v in _uptimes(fams).items():
            old = last_up.get(key)
            if old is not None and v < old:
                out.append({"who": key, "kind": "uptime_reset",
                            "t": round(t, 3),
                            "from_s": round(old, 3),
                            "to_s": round(v, 3)})
            last_up[key] = v
    return out


def _counter_deltas(samples: List[Tuple[float, Dict[str, dict]]],
                    t0: float, t1: float,
                    restart_ts: List[float]
                    ) -> Tuple[Dict[str, float], List[str]]:
    """Sum of per-scrape-pair counter deltas inside [t0, t1].
    Negative deltas clamp to 0; a clamp in a window with NO detected
    restart is reported as an unexplained reset."""
    totals: Dict[str, float] = {}
    unexplained: List[str] = []
    window = [(t, f) for t, f in samples if t0 <= t <= t1]
    for i in range(1, len(window)):
        t_prev, prev = window[i - 1]
        t_cur, cur = window[i]
        cv_prev, cv_cur = counter_values(prev), counter_values(cur)
        restarted = any(t_prev < rt <= t_cur for rt in restart_ts)
        for key, v in cv_cur.items():
            old = cv_prev.get(key)
            if old is None:
                continue
            d = v - old
            if d < 0:
                if not restarted:
                    unexplained.append(
                        f"{key}: {old:g} -> {v:g} at t={t_cur:.3f}")
                d = max(0.0, v)   # restarted process: count its new total
            totals[key] = totals.get(key, 0.0) + d
    return totals, unexplained


def _gauge_series(samples, t0, t1, family: str,
                  match: Dict[str, str]) -> List[float]:
    out: List[float] = []
    for t, fams in samples:
        if not t0 <= t <= t1:
            continue
        for labels, v in (fams.get(family) or
                          {"samples": []})["samples"]:
            if all(labels.get(k) == v2 for k, v2 in match.items()):
                out.append(v)
    return out


def error_budget(samples: List[Tuple[float, Dict[str, dict]]],
                 t0: float, t1: float, slo: dict,
                 restarts: Optional[List[dict]] = None) -> dict:
    """Scrape-based SLO verdict for one phase window [t0, t1].

    Error budget: with availability target A over N observed routed
    requests, the budget is (1-A)*N failed attempts; consumption is
    the router-observed per-replica failure delta (retries the router
    absorbed still consume budget — they cost capacity and tail).
    Latency: the fleet's route-stage p99 gauge must sit within
    slo.p99_ms for every scrape of the window (the gauge is already a
    moving percentile over the bounded ring)."""
    restarts = restarts if restarts is not None \
        else detect_restarts(samples)
    rts = [r["t"] for r in restarts]
    deltas, unexplained = _counter_deltas(samples, t0, t1, rts)

    def total(prefix: str, match: str = "") -> float:
        return sum(v for k, v in deltas.items()
                   if k.startswith(prefix) and match in k)

    routed = total("cos_routed_total|", "role=router")
    failures = total("cos_replica_failures_total|", "role=router")
    retries = total("cos_retries_total|", "role=router")
    hedges = total("cos_hedges_fired_total|", "role=router")
    observed = routed + failures
    avail = float(slo.get("availability", 0.999))
    budget = (1.0 - avail) * observed
    p99_target = float(slo.get("p99_ms", 0.0))
    p99s = _gauge_series(samples, t0, t1, "cos_stage_ms",
                         {"role": "router", "stage": "route",
                          "quantile": "0.99"})
    p99_worst = max(p99s) if p99s else None
    in_window = [r for r in restarts if t0 <= r["t"] <= t1]
    out = {
        "routed": routed, "failures": failures,
        "retries": retries, "hedges_fired": hedges,
        "scrapes": sum(1 for t, _ in samples if t0 <= t <= t1),
        "availability_slo": avail,
        "error_budget": round(budget, 3),
        "budget_consumed": failures,
        "budget_ok": failures <= budget or failures == 0,
        "p99_target_ms": p99_target,
        "p99_worst_ms": round(p99_worst, 3)
        if p99_worst is not None else None,
        "p99_ok": (p99_worst is not None
                   and p99_worst <= p99_target) if p99_target else None,
        "restarts": in_window,
        "unexplained_counter_resets": unexplained,
    }
    out["slo_ok"] = bool(out["budget_ok"]
                         and out["p99_ok"] is not False
                         and not unexplained)
    return out


# ---------------------------------------------------------------------------
# incident reconstruction
# ---------------------------------------------------------------------------

def _match(ev: dict, source: str, event: str, **attrs) -> bool:
    if ev.get("source") != source or ev.get("event") != event:
        return False
    for k, v in attrs.items():
        if ev.get(k) != v:
            return False
    return True


def _expectations(fault: dict) -> Optional[Tuple[Callable, Callable]]:
    """(evidence_predicate, recovery_predicate) for one injected
    fault record — the reconstruction CONTRACT: which recorder events
    prove the fault actually landed and which prove the system
    recovered from it."""
    kind = fault["kind"]
    rep = f"replica{fault.get('replica')}" \
        if fault.get("replica") is not None else None
    if kind == "replica_kill":
        return (lambda e: _match(e, "fleet", "replica_died",
                                 replica=rep),
                lambda e: _match(e, "fleet", "replica_rejoined",
                                 replica=rep))
    if kind == "replica_slow":
        def ev_set(e):
            return (_match(e, "fleet", "replica_fault_set",
                           replica=rep)
                    and (e.get("env") or {}).get(
                        "COS_FAULT_REPLICA_SLOW"))

        def ev_clear(e):
            return (_match(e, "fleet", "replica_fault_set",
                           replica=rep)
                    and not (e.get("env") or {}).get(
                        "COS_FAULT_REPLICA_SLOW"))
        return ev_set, ev_clear
    if kind == "flaky_storage":
        def st_set(e):
            return (_match(e, "chaos", "faults_applied")
                    and (e.get("env") or {}).get(
                        "COS_FAULT_FLAKY_STORAGE"))

        def st_clear(e):
            return (_match(e, "chaos", "faults_applied")
                    and "COS_FAULT_FLAKY_STORAGE" in (e.get("env")
                                                      or {})
                    and not (e.get("env") or {}).get(
                        "COS_FAULT_FLAKY_STORAGE"))
        return st_set, st_clear
    if kind == "snapshot_truncate":
        return (lambda e: _match(e, "chaos", "snapshot_truncate"),
                lambda e: _match(e, "deploy", "round"))
    if kind == "canary_kill":
        return (lambda e: _match(e, "chaos", "canary_kill"),
                lambda e: (_match(e, "deploy", "round")
                           and e.get("verdict") in ("aborted",
                                                    "reject",
                                                    "skipped")))
    if kind == "reload_fail":
        return (lambda e: _match(e, "chaos", "reload_fail"),
                lambda e: _match(e, "fleet", "rollback_done"))
    return None      # deploy_round etc.: an action, not a fault


def reconstruct_incidents(timeline: List[dict], injected: List[dict],
                          recovery_deadline_s: float = 60.0) -> dict:
    """Walk the merged recorder timeline and EXPLAIN every injected
    fault: its evidence event must appear at/after the injection
    wall-time and its recovery event within `recovery_deadline_s` of
    the evidence.  Faults without expectations (deploy_round) pass
    through as actions.  The whole day fails reconstruction if any
    fault stays unexplained — a chaos knob that silently did nothing
    is as much a harness bug as a fault nothing recovered from."""
    incidents: List[dict] = []
    for fault in injected:
        exp = _expectations(fault)
        if exp is None:
            continue
        ev_pred, rec_pred = exp
        t_inj = fault["t_wall"]
        # small slack absorbs clock granularity between processes
        evidence = next((e for e in timeline
                         if e.get("ts", 0) >= t_inj - 0.25
                         and ev_pred(e)), None)
        recovery = None
        if evidence is not None:
            t_ev = evidence.get("ts", t_inj)
            recovery = next(
                (e for e in timeline
                 if t_ev <= e.get("ts", 0)
                 <= t_ev + recovery_deadline_s
                 and e is not evidence and rec_pred(e)), None)
        incidents.append({
            "fault": {k: v for k, v in fault.items()
                      if k != "t_wall"},
            "t_injected": round(t_inj, 3),
            "evidence": evidence,
            "recovery": recovery,
            "recovery_s": round(recovery["ts"] - evidence["ts"], 3)
            if recovery and evidence else None,
            "explained": bool(evidence is not None
                              and recovery is not None),
        })
    return {
        "events_merged": len(timeline),
        "faults_injected": len(incidents),
        "explained": sum(1 for i in incidents if i["explained"]),
        "ok": all(i["explained"] for i in incidents),
        "incidents": incidents,
    }


def slow_exemplars(results, fetch_traces: Callable[[str], List[dict]],
                   n: int = 3) -> List[dict]:
    """The day's N slowest successful client requests, each with the
    span decomposition pulled back through /v1/traces?trace=<id> —
    the artifact shows WHERE the worst latency went, not just that it
    happened."""
    traced = [r for r in results
              if r.trace_id and 200 <= r.status < 300]
    worst = sorted(traced, key=lambda r: -r.lat_ms)[:n]
    out = []
    for r in worst:
        try:
            spans = fetch_traces(r.trace_id)
        except Exception as e:       # noqa: BLE001 — best-effort
            spans = [{"error": f"{type(e).__name__}: {e}"}]
        out.append({"trace_id": r.trace_id,
                    "lat_ms": r.lat_ms, "tenant": r.tenant,
                    "spans": spans})
    return out
