"""Production-day replay harness (ROADMAP item: whole-system drill).

Every subsystem has its own drill (chaos, canary, tail-latency,
sync-mode); `prodday` exercises them TOGETHER on a compressed
wall-clock and turns the PR 15 observability substrate — tracing,
flight recorder, Prometheus exposition — into a verdict engine:

  scenario.py   a scenario is a checked-in JSON data file (phases,
                load shapes, scheduled chaos) validated with
                line-precise errors — new scenarios are data, not code
  traffic.py    open-loop traffic generator: diurnal/flash load
                curves, zipfian payload mix, malformed-payload
                injection, per-class tenants
  engine.py     runs a scenario against a live stack (fleet router +
                optional deploy loop), firing scheduled faults through
                the COS_FAULT_* runtime hooks
  verdict.py    per-phase SLO / error-budget accounting from periodic
                prom scrapes, incident reconstruction from merged
                flight-recorder dumps, slow-request trace exemplars
  leaks.py      end-of-day leak gates: fds, child processes, threads,
                registry residency vs start-of-day

Knobs: COS_PRODDAY_SCRAPE_S, COS_PRODDAY_RECOVERY_S,
COS_PRODDAY_EXEMPLARS, COS_PRODDAY_INFLIGHT (docs/tuning.md).
"""

from .engine import ProdDay, FleetStack                    # noqa: F401
from .leaks import leak_gates, snapshot_leaks              # noqa: F401
from .scenario import (Scenario, ScenarioError,            # noqa: F401
                       load_scenario, parse_scenario)
from .traffic import TrafficGen                            # noqa: F401
from .verdict import (PromScraper, error_budget,           # noqa: F401
                      reconstruct_incidents)
