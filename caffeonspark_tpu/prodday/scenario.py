"""Scenario files: a production day as a checked-in JSON data file.

The north star wants scenario DIVERSITY to be cheap: a new production
day — different load curve, different chaos schedule — must be a new
data file, never new code.  A scenario is phases on a compressed
wall-clock; each phase sets a load shape (traffic.py) and schedules
chaos through the existing COS_FAULT_* knobs (engine.py fires them
via the runtime hooks: `Fleet.kill_replica`, POST /v1/faults,
`DeployController.refresh_faults`).

Validation is LINE-PRECISE: the stdlib json module reports positions
only for syntax errors, so this module parses JSON itself (same
grammar, ~recursive descent) keeping the source line of every key and
element.  A bad phase, an unknown fault kind, or two overlapping
stateful-fault windows each reject with `file.json:LINE: message` —
an operator editing a 200-line scenario gets pointed at the line, not
at "phase 7 somewhere".

Schema (all times in seconds on the compressed clock):

  {"name": str, "seed": int?, "scrape_interval_s": num?,
   "slo": {"p99_ms": num, "availability": num in (0,1]},
   "phases": [
     {"name": str, "duration_s": num > 0,
      "load": {"shape": "flat"|"ramp"|"diurnal"|"flash",
               "rps": num > 0, "floor": num in [0,1]?,
               "spike_x": num >= 1?, "spike_at": [0,1]?,
               "spike_frac": (0,1]?,
               "zipf": {"pool": int, "hot": int, "hit_rate": [0,1]}?,
               "malformed_p": [0,1)?, "tenants": [...]?},
      "faults": [{"at_s": num, "kind": <kind>, ...}]?,
      "slo": {...}?}]}

Fault kinds (each maps onto one existing COS_FAULT_* knob or fleet
hook; stateful kinds carry a `clear_at_s` window):

  replica_kill       SIGKILL replica N (fleet monitor must respawn)
  replica_slow       COS_FAULT_REPLICA_SLOW straggler, factor×,
                     staged/lifted via POST /v1/faults
  flaky_storage      COS_FAULT_FLAKY_STORAGE on the deploy loop
  snapshot_truncate  COS_FAULT_SNAPSHOT_TRUNCATE (next deploy round)
  canary_kill        COS_FAULT_CANARY_KILL after N mirrored requests
  reload_fail        COS_FAULT_RELOAD_FAIL_RANK mid-roll kill
  deploy_round       run one full stream→fine-tune→canary→roll round
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

FAULT_KINDS = ("replica_kill", "replica_slow", "flaky_storage",
               "snapshot_truncate", "canary_kill", "reload_fail",
               "deploy_round")
# stateful kinds hold a window [at_s, clear_at_s); overlapping windows
# of the same kind on the same target are a scenario bug (the second
# set would clobber the first's clear)
STATEFUL_KINDS = ("replica_slow", "flaky_storage")
LOAD_SHAPES = ("flat", "ramp", "diurnal", "flash")


class ScenarioError(ValueError):
    """Validation failure with the offending source line."""

    def __init__(self, msg: str, line: int = 0, path: str = ""):
        self.line = line
        self.path = path
        where = f"{path or '<scenario>'}:{line}: " if line else ""
        super().__init__(where + msg)


# ---------------------------------------------------------------------------
# Annotated JSON: same values as json.loads, plus source lines
# ---------------------------------------------------------------------------

class AnnDict(dict):
    """A parsed JSON object that remembers its own source line and the
    line of every key."""
    __slots__ = ("line", "keylines")


class AnnList(list):
    """A parsed JSON array that remembers its own source line and the
    line of every element."""
    __slots__ = ("line", "itemlines")


class _Parser:
    """Minimal recursive-descent JSON parser tracking line numbers.
    Grammar-complete for the JSON this repo checks in; number/string
    token parsing delegates to json.loads on the token text so escape
    and float semantics are exactly the stdlib's."""

    def __init__(self, text: str):
        self.text = text
        self.i = 0
        self.line = 1

    def error(self, msg: str) -> ScenarioError:
        return ScenarioError(msg, line=self.line)

    def _skip_ws(self) -> None:
        while self.i < len(self.text):
            c = self.text[self.i]
            if c == "\n":
                self.line += 1
            elif c not in " \t\r":
                return
            self.i += 1

    def _expect(self, ch: str) -> None:
        self._skip_ws()
        if self.i >= len(self.text) or self.text[self.i] != ch:
            got = (self.text[self.i] if self.i < len(self.text)
                   else "end of file")
            raise self.error(f"expected {ch!r}, got {got!r}")
        self.i += 1

    def _peek(self) -> str:
        self._skip_ws()
        return self.text[self.i] if self.i < len(self.text) else ""

    def parse(self):
        value = self._value()
        self._skip_ws()
        if self.i < len(self.text):
            raise self.error("trailing data after the document")
        return value

    def _value(self):
        c = self._peek()
        if c == "{":
            return self._object()
        if c == "[":
            return self._array()
        if c == '"':
            return self._string()
        if c == "" :
            raise self.error("unexpected end of file")
        return self._literal()

    def _object(self) -> AnnDict:
        out = AnnDict()
        out.line = self.line
        out.keylines = {}
        self._expect("{")
        if self._peek() == "}":
            self.i += 1
            return out
        while True:
            self._skip_ws()
            key_line = self.line
            key = self._string()
            if key in out:
                raise ScenarioError(f"duplicate key {key!r}",
                                    line=key_line)
            self._expect(":")
            out[key] = self._value()
            out.keylines[key] = key_line
            c = self._peek()
            if c == ",":
                self.i += 1
                continue
            if c == "}":
                self.i += 1
                return out
            raise self.error("expected ',' or '}' in object")

    def _array(self) -> AnnList:
        out = AnnList()
        out.line = self.line
        out.itemlines = []
        self._expect("[")
        if self._peek() == "]":
            self.i += 1
            return out
        while True:
            self._skip_ws()
            out.itemlines.append(self.line)
            out.append(self._value())
            c = self._peek()
            if c == ",":
                self.i += 1
                continue
            if c == "]":
                self.i += 1
                return out
            raise self.error("expected ',' or ']' in array")

    def _string(self) -> str:
        self._skip_ws()
        if self._peek() != '"':
            raise self.error("expected a string")
        start = self.i
        self.i += 1
        while self.i < len(self.text):
            c = self.text[self.i]
            if c == "\\":
                self.i += 2
                continue
            if c == '"':
                self.i += 1
                try:
                    return json.loads(self.text[start:self.i])
                except ValueError as e:
                    raise self.error(f"bad string literal: {e}")
            if c == "\n":
                raise self.error("unterminated string")
            self.i += 1
        raise self.error("unterminated string")

    def _literal(self):
        start = self.i
        while (self.i < len(self.text)
               and self.text[self.i] not in " \t\r\n,}]"):
            self.i += 1
        tok = self.text[start:self.i]
        try:
            return json.loads(tok)
        except ValueError:
            raise self.error(f"bad literal {tok!r}")


def parse_annotated(text: str):
    """json.loads with line bookkeeping: containers come back as
    AnnDict/AnnList carrying `.line` / `.keylines` / `.itemlines`."""
    return _Parser(text).parse()


# ---------------------------------------------------------------------------
# Validation
# ---------------------------------------------------------------------------

def _line_of(container, key) -> int:
    if isinstance(container, AnnDict):
        return container.keylines.get(key, container.line)
    if isinstance(container, AnnList):
        try:
            return container.itemlines[key]
        except (IndexError, TypeError):
            return container.line
    return 0


def _err(msg: str, container, key, path: str) -> ScenarioError:
    return ScenarioError(msg, line=_line_of(container, key), path=path)


def _check_keys(obj, allowed, what: str, path: str) -> None:
    for k in obj:
        if k not in allowed:
            raise _err(f"{what}: unknown key {k!r} (allowed: "
                       f"{', '.join(sorted(allowed))})", obj, k, path)


def _num(obj, key, what, path, *, default=None, lo=None, hi=None,
         lo_open=False, hi_open=False, required=False):
    if key not in obj:
        if required:
            raise _err(f"{what}: missing required {key!r}", obj,
                       next(iter(obj), None), path)
        return default
    v = obj[key]
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise _err(f"{what}: {key!r} must be a number, got "
                   f"{type(v).__name__}", obj, key, path)
    if lo is not None and (v <= lo if lo_open else v < lo):
        raise _err(f"{what}: {key!r}={v} out of range", obj, key, path)
    if hi is not None and (v >= hi if hi_open else v > hi):
        raise _err(f"{what}: {key!r}={v} out of range", obj, key, path)
    return float(v)


class Tenant:
    __slots__ = ("name", "weight", "model", "lane")

    def __init__(self, name: str, weight: float,
                 model: Optional[str] = None,
                 lane: Optional[str] = None):
        self.name, self.weight, self.model = name, weight, model
        self.lane = lane

    def to_dict(self) -> dict:
        return {"name": self.name, "weight": self.weight,
                "model": self.model, "lane": self.lane}


class LoadSpec:
    """One phase's validated load block (traffic.py consumes this)."""

    __slots__ = ("shape", "rps", "floor", "spike_x", "spike_at",
                 "spike_frac", "zipf_pool", "zipf_hot", "zipf_hit",
                 "malformed_p", "tenants")

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw[k])

    def to_dict(self) -> dict:
        out = {k: getattr(self, k) for k in self.__slots__
               if k != "tenants"}
        out["tenants"] = [t.to_dict() for t in self.tenants]
        return out


class Fault:
    __slots__ = ("kind", "at_s", "clear_at_s", "replica", "factor",
                 "p", "after_requests")

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw.get(k))

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__
                if getattr(self, k) is not None}


class Phase:
    __slots__ = ("name", "duration_s", "load", "faults", "slo")

    def __init__(self, name, duration_s, load, faults, slo):
        self.name, self.duration_s = name, duration_s
        self.load, self.faults, self.slo = load, faults, slo

    def to_dict(self) -> dict:
        return {"name": self.name, "duration_s": self.duration_s,
                "load": self.load.to_dict(),
                "faults": [f.to_dict() for f in self.faults],
                "slo": dict(self.slo)}


class Scenario:
    __slots__ = ("name", "seed", "scrape_interval_s", "slo", "phases")

    def __init__(self, name, seed, scrape_interval_s, slo, phases):
        self.name, self.seed = name, seed
        self.scrape_interval_s = scrape_interval_s
        self.slo, self.phases = slo, phases

    @property
    def duration_s(self) -> float:
        return sum(p.duration_s for p in self.phases)

    def to_dict(self) -> dict:
        return {"name": self.name, "seed": self.seed,
                "scrape_interval_s": self.scrape_interval_s,
                "slo": dict(self.slo),
                "phases": [p.to_dict() for p in self.phases]}


def _validate_slo(obj, path: str, what: str,
                  base: Optional[dict] = None) -> dict:
    _check_keys(obj, {"p99_ms", "availability"}, what, path)
    out = dict(base or {})
    p99 = _num(obj, "p99_ms", what, path, lo=0, lo_open=True,
               required=base is None)
    avail = _num(obj, "availability", what, path, lo=0, hi=1,
                 lo_open=True, required=base is None)
    if p99 is not None:
        out["p99_ms"] = p99
    if avail is not None:
        out["availability"] = avail
    return out


def _validate_tenants(arr, path: str, what: str) -> List[Tenant]:
    if not isinstance(arr, list) or not arr:
        raise ScenarioError(f"{what}: 'tenants' must be a non-empty "
                            "array",
                            line=getattr(arr, "line", 0), path=path)
    out = []
    for i, t in enumerate(arr):
        tw = f"{what} tenant[{i}]"
        if not isinstance(t, dict):
            raise _err(f"{tw}: must be an object", arr, i, path)
        _check_keys(t, {"name", "weight", "model", "lane"}, tw, path)
        name = t.get("name")
        if not isinstance(name, str) or not name:
            raise _err(f"{tw}: 'name' must be a non-empty string",
                       t, "name" if "name" in t else None, path)
        weight = _num(t, "weight", tw, path, default=1.0, lo=0,
                      lo_open=True)
        model = t.get("model")
        if model is not None and not isinstance(model, str):
            raise _err(f"{tw}: 'model' must be a string or null",
                       t, "model", path)
        lane = t.get("lane")
        if lane is not None and lane not in ("interactive", "batch"):
            raise _err(f"{tw}: 'lane' must be \"interactive\" or "
                       "\"batch\"", t, "lane", path)
        out.append(Tenant(name, weight, model, lane))
    return out


def _validate_load(obj, path: str, what: str) -> LoadSpec:
    if not isinstance(obj, dict):
        raise ScenarioError(f"{what}: 'load' must be an object",
                            line=getattr(obj, "line", 0), path=path)
    _check_keys(obj, {"shape", "rps", "floor", "spike_x", "spike_at",
                      "spike_frac", "zipf", "malformed_p", "tenants"},
                what, path)
    shape = obj.get("shape", "flat")
    if shape not in LOAD_SHAPES:
        raise _err(f"{what}: unknown load shape {shape!r} (allowed: "
                   f"{', '.join(LOAD_SHAPES)})", obj, "shape", path)
    rps = _num(obj, "rps", what, path, lo=0, lo_open=True,
               required=True)
    floor = _num(obj, "floor", what, path, default=0.25, lo=0, hi=1)
    spike_x = _num(obj, "spike_x", what, path, default=4.0, lo=1)
    spike_at = _num(obj, "spike_at", what, path, default=0.5, lo=0,
                    hi=1)
    spike_frac = _num(obj, "spike_frac", what, path, default=0.2,
                      lo=0, hi=1, lo_open=True)
    zipf = obj.get("zipf") or {}
    if not isinstance(zipf, dict):
        raise _err(f"{what}: 'zipf' must be an object", obj, "zipf",
                   path)
    if zipf:
        _check_keys(zipf, {"pool", "hot", "hit_rate"},
                    f"{what} zipf", path)
    pool = int(_num(zipf, "pool", f"{what} zipf", path, default=16,
                    lo=1))
    hot = int(_num(zipf, "hot", f"{what} zipf", path, default=4,
                   lo=1))
    hit = _num(zipf, "hit_rate", f"{what} zipf", path, default=0.0,
               lo=0, hi=1)
    if hot > pool:
        raise _err(f"{what} zipf: hot={hot} exceeds pool={pool}",
                   zipf, "hot" if "hot" in zipf else "pool", path)
    malformed_p = _num(obj, "malformed_p", what, path, default=0.0,
                       lo=0, hi=1, hi_open=True)
    tenants = (_validate_tenants(obj["tenants"], path, what)
               if "tenants" in obj
               else [Tenant("default", 1.0)])
    return LoadSpec(shape=shape, rps=rps, floor=floor,
                    spike_x=spike_x, spike_at=spike_at,
                    spike_frac=spike_frac, zipf_pool=pool,
                    zipf_hot=hot, zipf_hit=hit,
                    malformed_p=malformed_p, tenants=tenants)


_FAULT_KEYS: Dict[str, set] = {
    "replica_kill": {"at_s", "kind", "replica"},
    "replica_slow": {"at_s", "kind", "replica", "factor",
                     "clear_at_s"},
    "flaky_storage": {"at_s", "kind", "p", "clear_at_s"},
    "snapshot_truncate": {"at_s", "kind"},
    "canary_kill": {"at_s", "kind", "after_requests"},
    "reload_fail": {"at_s", "kind", "replica"},
    "deploy_round": {"at_s", "kind"},
}


def _validate_fault(obj, arr, i: int, duration_s: float, path: str,
                    what: str) -> Fault:
    if not isinstance(obj, dict):
        raise _err(f"{what}: must be an object", arr, i, path)
    kind = obj.get("kind")
    if kind not in FAULT_KINDS:
        raise _err(f"{what}: unknown fault kind {kind!r} (known: "
                   f"{', '.join(FAULT_KINDS)})", obj,
                   "kind" if "kind" in obj else None, path)
    _check_keys(obj, _FAULT_KEYS[kind], what, path)
    at_s = _num(obj, "at_s", what, path, required=True, lo=0)
    if at_s >= duration_s:
        raise _err(f"{what}: at_s={at_s:g} is at/after the phase end "
                   f"(duration_s={duration_s:g})", obj, "at_s", path)
    clear = _num(obj, "clear_at_s", what, path)
    if clear is not None:
        if kind not in STATEFUL_KINDS:
            raise _err(f"{what}: {kind!r} takes no clear_at_s", obj,
                       "clear_at_s", path)
        if clear <= at_s or clear > duration_s:
            raise _err(f"{what}: clear_at_s={clear:g} must lie in "
                       f"(at_s, duration_s]", obj, "clear_at_s", path)
    f = Fault(kind=kind, at_s=at_s, clear_at_s=clear)
    if kind in ("replica_kill", "replica_slow", "reload_fail"):
        f.replica = int(_num(obj, "replica", what, path,
                             required=True, lo=0))
    if kind == "replica_slow":
        f.factor = _num(obj, "factor", what, path, default=8.0, lo=1)
    if kind == "flaky_storage":
        f.p = _num(obj, "p", what, path, default=0.3, lo=0, hi=1,
                   hi_open=True)
    if kind == "canary_kill":
        f.after_requests = int(_num(obj, "after_requests", what, path,
                                    default=1, lo=0))
    return f


def _check_overlaps(faults: List[Fault], arr, path: str,
                    what: str) -> None:
    """Two stateful faults of the same kind on the same target with
    overlapping [at_s, clear_at_s) windows: the later set would
    clobber the earlier clear — reject with the later fault's line.
    Runs on the SOURCE order (pairwise — fault lists are small) so
    the reported line is the file's, not a sorted index's."""
    def window(f: Fault) -> Tuple[float, float]:
        return (f.at_s, f.clear_at_s if f.clear_at_s is not None
                else float("inf"))

    for i, f in enumerate(faults):
        if f.kind not in STATEFUL_KINDS:
            continue
        for j in range(i):
            g = faults[j]
            if (g.kind, g.replica) != (f.kind, f.replica):
                continue
            (a0, a1), (b0, b1) = window(g), window(f)
            if b0 < a1 and a0 < b1:
                raise _err(
                    f"{what}[{i}]: {f.kind} window "
                    f"[{b0:g}, {'inf' if b1 == float('inf') else format(b1, 'g')})"
                    f" overlaps the schedule at line "
                    f"{_line_of(arr, j)}", arr, i, path)


def _validate_phase(obj, arr, i: int, base_slo: dict,
                    path: str) -> Phase:
    what = f"phase[{i}]"
    if not isinstance(obj, dict):
        raise _err(f"{what}: must be an object", arr, i, path)
    _check_keys(obj, {"name", "duration_s", "load", "faults", "slo"},
                what, path)
    name = obj.get("name")
    if not isinstance(name, str) or not name:
        raise _err(f"{what}: 'name' must be a non-empty string", obj,
                   "name" if "name" in obj else None, path)
    what = f"phase[{i}] {name!r}"
    duration = _num(obj, "duration_s", what, path, required=True,
                    lo=0, lo_open=True)
    if "load" not in obj:
        raise _err(f"{what}: missing required 'load'", obj, "name",
                   path)
    load = _validate_load(obj["load"], path, what)
    faults_arr = obj.get("faults", AnnList())
    if not isinstance(faults_arr, list):
        raise _err(f"{what}: 'faults' must be an array", obj,
                   "faults", path)
    faults = [_validate_fault(f, faults_arr, j, duration, path,
                              f"{what} fault[{j}]")
              for j, f in enumerate(faults_arr)]
    _check_overlaps(faults, faults_arr, path, f"{what} fault")
    faults.sort(key=lambda f: f.at_s)
    slo = (_validate_slo(obj["slo"], path, f"{what} slo", base_slo)
           if "slo" in obj else dict(base_slo))
    return Phase(name, duration, load, faults, slo)


def parse_scenario(text: str, path: str = "") -> Scenario:
    """Parse + validate a scenario document; raises ScenarioError
    (with the offending line) on anything a run could trip over."""
    try:
        doc = parse_annotated(text)
    except ScenarioError as e:
        raise ScenarioError(str(e).split(": ", 1)[-1], line=e.line,
                            path=path)
    if not isinstance(doc, dict):
        raise ScenarioError("scenario must be a JSON object", line=1,
                            path=path)
    _check_keys(doc, {"name", "seed", "scrape_interval_s", "slo",
                      "phases"}, "scenario", path)
    name = doc.get("name")
    if not isinstance(name, str) or not name:
        raise _err("scenario: 'name' must be a non-empty string", doc,
                   "name" if "name" in doc else None, path)
    seed = int(_num(doc, "seed", "scenario", path, default=7))
    scrape = _num(doc, "scrape_interval_s", "scenario", path,
                  default=0.5, lo=0, lo_open=True)
    if "slo" not in doc or not isinstance(doc["slo"], dict):
        raise _err("scenario: missing required 'slo' object", doc,
                   "slo" if "slo" in doc else "name", path)
    slo = _validate_slo(doc["slo"], path, "scenario slo")
    phases_arr = doc.get("phases")
    if not isinstance(phases_arr, list) or not phases_arr:
        raise _err("scenario: 'phases' must be a non-empty array",
                   doc, "phases" if "phases" in doc else "name", path)
    phases = [_validate_phase(p, phases_arr, i, slo, path)
              for i, p in enumerate(phases_arr)]
    names = [p.name for p in phases]
    if len(set(names)) != len(names):
        dup = next(n for n in names if names.count(n) > 1)
        raise _err(f"scenario: duplicate phase name {dup!r}",
                   phases_arr, names.index(dup), path)
    return Scenario(name, seed, scrape, slo, phases)


def load_scenario(path: str) -> Scenario:
    with open(path) as f:
        return parse_scenario(f.read(), path=path)
