"""Roofline-guided per-layer precision/layout/fusion autotuner.

MFU across the zoo sits at 0.19–0.51 (BENCH_r05) and the knobs that
close the gap — conv layout (NCHW/NHWC/space-to-depth), per-layer
compute dtype, the fused ReLU(+bias)+LRN stem epilogue, flash vs
reference attention, int8 serving matmuls — were global, opt-in and
hand-picked.  This module picks them PER LAYER, by measurement:

  1. rank layers with the roofline model (analysis/roofline.py):
     MXU-bound layers are precision candidates, HBM-bound layers are
     layout/fusion candidates; only the top offenders get measured
     (the tail can't move the step time, so it stays default);
  2. for each ranked layer, enumerate the LEGAL variants (dtype flips
     never touch f32_stats layers — the COS002 precision-floor
     discipline; int8 is serving-forward-only; fusion only where the
     net's peephole proves the producer chain eligible);
  3. A/B each variant by MEASURED steps/s at a pinned numerics
     tolerance against the untuned net — a variant that drifts past
     the tolerance is rejected no matter how fast it is;
  4. the winning plan is a JSON artifact cached per (net digest,
     device_kind, batch, dtype policy), applied at net-build time
     through the layer-op context (`Net(..., autotune=...)` /
     `COS_AUTOTUNE`), and published as `info.autotune` in
     PipelineMetrics so every bench artifact is self-describing.

COS_AUTOTUNE semantics (resolved ONCE at Net construction — never at
trace time, the COS003 discipline):
  * unset / "0"  — INERT: no plan, no variants, training byte-identical;
  * "1"          — apply the cached plan for this net's digest (no
                   cached plan: log and run untuned — tuning is an
                   explicit act, `autotune_net` / `make bench-autotune`,
                   never a construction-time surprise);
  * <path>       — apply that plan file.

Injected floor (CPU benches): COS_AUTOTUNE_FLOOR_GBS (or the
`floor_gbs` argument) models an HBM-bandwidth regime by sleeping
modeled_step_bytes/floor after every measured step — the same
floor-model technique bench_steploop's per-dispatch floor and
bench_gradsync's comm floor use, so byte-reducing variants show their
uplift on hardware whose own memory system isn't the bottleneck.  The
floor applies identically to baseline and candidates and is recorded
in the plan.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import time
from typing import Dict, List, Optional, Tuple

_LOG = logging.getLogger(__name__)

PLAN_SCHEMA = "cos-autotune-plan"
PLAN_VERSION = 1

# layer types the tuner knows variants for
TUNABLE_TYPES = ("Convolution", "InnerProduct", "LRN",
                 "MultiHeadAttention")

# the ambient env knobs that shape the MEASURED baseline and every
# non-variant layer: recorded in the plan key at tune time, compared
# (warn-only) at apply time — a plan measured under COS_CONV_LAYOUT=
# NHWC applied in a bare shell runs its non-variant convs in a regime
# nobody measured.  COS_SERVE_WEIGHT_DTYPE matters the same way for
# serve-mode plans resolved per model: under int8/bf16 RESIDENCY
# (serving/quant.py) the InnerProduct weight arrives pre-quantized and
# the int8 variant's per-call weight-quantization cost — which the
# tuner's A/B measured — is gone, so a plan tuned in one regime and
# applied in the other states the mismatch instead of silently
# reporting stale numbers
AMBIENT_ENV_KNOBS = ("COS_CONV_LAYOUT", "COS_CONV_S2D",
                     "COS_FUSE_RELU_LRN", "COS_FUSE_BIAS_RELU_LRN",
                     "COS_SERVE_WEIGHT_DTYPE")


def ambient_env() -> Dict[str, str]:
    return {k: os.environ[k] for k in AMBIENT_ENV_KNOBS
            if os.environ.get(k) is not None}


# ---------------------------------------------------------------------------
# plan identity + cache
# ---------------------------------------------------------------------------

def net_digest(net_param) -> str:
    """Digest of the net topology (the aot.py idiom): the prototxt
    carries layer geometry AND data-layer batch sizes, so one digest
    identifies the tuned program shape."""
    return hashlib.sha256(str(net_param).encode()).hexdigest()[:16]


def dtype_policy_str(dtype, compute_dtype=None) -> str:
    """THE one grammar for the plan key's dtype-policy term — net.py's
    resolve hook and the tuner's plan key must agree or COS_AUTOTUNE=1
    silently fails open to an untuned run (cache filename mismatch)."""
    import jax.numpy as jnp
    return (f"{jnp.dtype(dtype).name}/"
            f"{jnp.dtype(compute_dtype if compute_dtype is not None else dtype).name}")


def device_kind() -> str:
    try:
        import jax
        return str(getattr(jax.devices()[0], "device_kind",
                           jax.default_backend()))
    except Exception:  # noqa: BLE001 — identity probe must never raise
        return "unknown"


def cache_root() -> str:
    return os.environ.get("COS_AUTOTUNE_CACHE", "artifacts/autotune")


def _slug(s: str) -> str:
    return "".join(c if c.isalnum() else "-" for c in str(s).lower())


def cache_path(digest: str, dev_kind: Optional[str] = None,
               root: Optional[str] = None, mode: str = "train",
               dtype_policy: str = "float32/float32") -> str:
    """One cache slot per (digest, device, mode, dtype policy): a
    serve-tuned plan (forward-only measurements, int8 variants) and a
    train-tuned plan of the same prototxt must never overwrite or
    cross-apply, and neither must f32- and bf16-policy tunes."""
    dev = _slug(dev_kind if dev_kind is not None else device_kind())
    return os.path.join(
        root or cache_root(),
        f"plan-{digest}-{dev}-{_slug(mode)}-{_slug(dtype_policy)}.json")


def plan_cache_path(plan: dict, root: Optional[str] = None) -> str:
    """The cache slot a plan's own key addresses."""
    key = plan.get("key", {})
    return cache_path(key["net_digest"], key.get("device_kind"),
                      root=root, mode=key.get("mode", "train"),
                      dtype_policy=key.get("dtype_policy",
                                           "float32/float32"))


def save_plan(plan: dict, path: Optional[str] = None) -> str:
    """Write the plan artifact (atomic tmp+rename) to `path` or its
    cache slot; returns the path."""
    if path is None:
        path = plan_cache_path(plan)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(plan, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def load_plan(path: str) -> dict:
    with open(path) as f:
        plan = json.load(f)
    if plan.get("schema") != PLAN_SCHEMA:
        raise ValueError(f"{path}: not a {PLAN_SCHEMA} artifact "
                         f"(schema={plan.get('schema')!r})")
    return plan


def resolve_plan(net_param, state, autotune,
                 dtype_policy: str = "float32/float32"
                 ) -> Tuple[Optional[dict], Dict[str, dict]]:
    """Net-construction hook: (plan, {layer: variant}) for this net.
    `autotune`: None defers to COS_AUTOTUNE (unset/"0" = inert), True
    behaves like COS_AUTOTUNE=1, a str is a plan path, a dict an
    explicit plan.  The cache lookup is keyed by (digest, device,
    mode, dtype policy) — mode from `state.phase` (TRAIN nets read
    train-tuned plans, TEST nets serve-tuned ones).  A plan whose key
    names a DIFFERENT net digest is ignored with a warning
    (force=true in the plan overrides — cross-net application is a
    measured risk the operator takes explicitly)."""
    from ..proto.caffe import Phase

    def _from_cache():
        mode = ("train" if state is None or state.phase == Phase.TRAIN
                else "serve")
        path = cache_path(net_digest(net_param), mode=mode,
                          dtype_policy=dtype_policy)
        if not os.path.exists(path):
            _LOG.info(
                "COS_AUTOTUNE=1: no cached plan at %s — run "
                "scripts/bench_autotune.py (or ops.autotune."
                "autotune_net) to tune this net; running untuned",
                path)
            return None, None
        return load_plan(path), f"cache:{path}"

    plan = None
    source = None
    if isinstance(autotune, dict):
        plan, source = autotune, autotune.get("source", "explicit")
    elif isinstance(autotune, str):
        plan, source = load_plan(autotune), f"file:{autotune}"
    elif autotune is True:
        plan, source = _from_cache()
        if plan is None:
            return None, {}
    else:
        env = os.environ.get("COS_AUTOTUNE", "")
        if env in ("", "0"):
            return None, {}
        if env == "1":
            plan, source = _from_cache()
            if plan is None:
                return None, {}
        else:
            plan, source = load_plan(env), f"file:{env}"
    key = plan.get("key", {})
    want = key.get("net_digest")
    have = net_digest(net_param)
    if want and want != have and not plan.get("force"):
        _LOG.warning(
            "autotune plan is for net digest %s, this net is %s — "
            "ignoring the plan (set force=true in the plan to apply "
            "anyway)", want, have)
        return None, {}
    tuned_env = key.get("env")
    if tuned_env is not None and tuned_env != ambient_env():
        # warn-only: the plan still applies, but its measured uplift /
        # parity described a DIFFERENT ambient regime for the
        # non-variant layers — the operator should re-tune or align
        _LOG.warning(
            "autotune plan was measured under env %s but the current "
            "regime is %s — non-variant layers run an unmeasured "
            "configuration; re-tune or align the knobs",
            tuned_env, ambient_env())
    if source:
        # the RESOLUTION route (cache:/file:/explicit) — the plan's own
        # provenance ("tuned") stays inside the artifact on disk
        plan = dict(plan, source=source)
    return plan, {n: dict(v) for n, v in plan.get("layers", {}).items()}


# ---------------------------------------------------------------------------
# variant enumeration
# ---------------------------------------------------------------------------

def _conv_variants(net, lp, *, dtype_flip: Optional[str]) -> List[dict]:
    from .layers import _conv_geometry, _s2d_geometry_ok
    cp = lp.convolution_param
    s2d_ok = False
    try:
        (kh, kw), (sh, sw), _, (dh, dw) = _conv_geometry(cp)
        c_in = net.blob_shapes[lp.bottom[0]][1]
        s2d_ok = _s2d_geometry_ok(c_in, cp, kh, kw, sh, sw, dh, dw)
    except Exception:  # noqa: BLE001 — geometry probe only prunes
        pass
    # enumerate the layouts that DIFFER from this layer's ambient
    # (env-resolved) path: under COS_CONV_LAYOUT=NHWC the useful
    # candidate is pinning BACK to nchw, and A/B-ing nhwc against
    # itself would just be a wasted compile that noise can accept
    if os.environ.get("COS_CONV_LAYOUT", "NCHW").upper() == "NHWC":
        amb = "nhwc"
    else:
        env_s2d = os.environ.get("COS_CONV_S2D")
        if env_s2d is not None:
            s2d_on = env_s2d == "1"
        else:
            from .pallas_kernels import pallas_enabled
            s2d_on = pallas_enabled()
        amb = "s2d" if (s2d_on and s2d_ok) else "nchw"
    candidates = ["nchw", "nhwc"] + (["s2d"] if s2d_ok else [])
    out: List[dict] = [{"layout": lo} for lo in candidates if lo != amb]
    if dtype_flip:
        out.append({"dtype": dtype_flip})
    return out


def _lrn_variants(net, lp) -> List[dict]:
    # eligibility IS net.py's peephole rule — the shared predicates,
    # not a re-implementation.  A looser probe would enumerate
    # variants the candidate build then silently refuses, and under
    # the injected-floor regime the byte model would credit the no-op
    # with a fake uplift.
    from ..net import fusable_relu_for_lrn, prefuse_conv_bias_eligible
    relu = fusable_relu_for_lrn(net.compute_layers, lp)
    if relu is None:
        return []
    out: List[dict] = [{"fuse": "relu"}]
    if prefuse_conv_bias_eligible(net.compute_layers, lp, relu):
        out.append({"fuse": "bias_relu"})
    return out


def legal_variants(net, lp, *, mode: str = "train",
                   allow_dtype: bool = True) -> List[dict]:
    """The legal variant dicts for one layer of `net` (excluding the
    implicit default {}).  `mode` 'serve' additionally admits the int8
    forward matmul for InnerProduct.  The dtype flip goes AGAINST the
    net-wide policy: bf16 candidates on an f32 net (HBM relief), f32
    candidates on a bf16 net (the precision pin — Ctx.precision()
    computes such layers at HIGHEST, so a sensitive layer can buy
    accuracy back if the measured A/B tolerates the cost)."""
    import jax.numpy as jnp
    t = lp.type
    f32_net = jnp.dtype(net.compute_dtype) == jnp.dtype(jnp.float32)
    dtype_flip = (("bfloat16" if f32_net else "float32")
                  if allow_dtype else None)
    if t == "Convolution":
        return _conv_variants(net, lp, dtype_flip=dtype_flip)
    if t == "InnerProduct":
        out = [{"dtype": dtype_flip}] if dtype_flip else []
        if mode == "serve":
            out.append({"int8": True})
        return out
    if t == "LRN":
        return _lrn_variants(net, lp)
    if t == "MultiHeadAttention":
        return [{"attention": "reference"}]
    return []


# ---------------------------------------------------------------------------
# measurement
# ---------------------------------------------------------------------------

def _rand_inputs(net, seed: int = 0):
    import numpy as np
    import jax.numpy as jnp
    rs = np.random.RandomState(seed)
    out = {}
    for name, shape, kind in net.input_specs:
        if kind.startswith(("label", "int")):
            out[name] = jnp.zeros(shape, jnp.float32)
        else:
            out[name] = jnp.asarray(
                rs.randn(*shape).astype(np.float32))
    return out


def _build_step(net, mode: str):
    """One jitted measurement step for a candidate net: train =
    loss+grads (the training hot path without the optimizer — the
    tuner must not recurse into Solver, which builds Nets); serve =
    the blob forward."""
    import jax

    if mode == "serve":
        names = tuple(net.output_blobs)

        def fwd(params, inputs):
            blobs, _ = net.apply(params, inputs, train=False)
            return {n: blobs[n] for n in names}
        return jax.jit(fwd)

    rng = jax.random.key(0)

    def step(params, inputs):
        (loss, (blobs, _)), grads = jax.value_and_grad(
            lambda p: net.loss(p, inputs, train=True, rng=rng),
            has_aux=True)(params)
        return loss, {n: blobs[n] for n in net.output_blobs}, grads
    return jax.jit(step)


def _pull(out):
    import jax
    leaf = jax.tree_util.tree_leaves(out)[0]
    jax.device_get(leaf)


def _measure(step, args, *, iters: int, warmup: int,
             sleep_s: float = 0.0):
    for _ in range(max(0, warmup)):
        _pull(step(*args))
        if sleep_s:
            time.sleep(sleep_s)
    t0 = time.perf_counter()
    out = None
    for _ in range(iters):
        out = step(*args)
        _pull(out)
        if sleep_s:
            time.sleep(sleep_s)
    dt = time.perf_counter() - t0
    return iters / dt, out


def _ref_values(out):
    """f32 host copies of a step's comparable outputs (loss + output
    blobs; grads excluded — grad drift is bounded through the loss)."""
    import numpy as np
    import jax
    if isinstance(out, tuple):          # train: (loss, blobs, grads)
        loss, blobs = out[0], out[1]
        vals = {"loss": np.asarray(jax.device_get(loss), np.float32)}
    else:                               # serve: blobs
        blobs, vals = out, {}
    for n, v in blobs.items():
        vals[n] = np.asarray(jax.device_get(v), np.float32)
    return vals


def _parity(ref: dict, got: dict) -> float:
    """max over compared tensors of max|a−b| / (max|a| + 1e-6) — the
    pinned relative tolerance metric recorded in the plan."""
    import numpy as np
    worst = 0.0
    for n, a in ref.items():
        b = got.get(n)
        if b is None or a.shape != b.shape:
            return float("inf")
        denom = float(np.max(np.abs(a))) + 1e-6
        worst = max(worst, float(np.max(np.abs(a - b))) / denom)
    return worst


# ---------------------------------------------------------------------------
# the tuner
# ---------------------------------------------------------------------------

def autotune_net(net_param, *, state=None, dtype=None,
                 compute_dtype=None, mode: str = "train",
                 top_layers: int = 6, measure_iters: int = 3,
                 warmup: int = 1, tolerance: float = 5e-2,
                 min_uplift: float = 1.02,
                 floor_gbs: Optional[float] = None,
                 generalize: bool = True, save: bool = True,
                 cache_dir: Optional[str] = None, seed: int = 0) -> dict:
    """Tune one net; returns (and by default caches) the plan dict.

    Greedy coordinate descent over the roofline top offenders: each
    candidate plan is a real Net build + jit + measured steps/s, gated
    on `_parity(...) <= tolerance` against the untuned baseline.  With
    `generalize`, a layer's winning variant is propagated to its
    (type, roofline-bound) class and the composed plan re-measured —
    falling back to the measured-only plan if the propagation regresses
    or breaks parity."""
    import jax
    import jax.numpy as jnp
    from ..analysis import roofline as rl
    from ..net import Net
    from ..proto.caffe import NetState, Phase

    state = state or NetState(phase=Phase.TRAIN
                              if mode == "train" else Phase.TEST)
    dtype = dtype or jnp.float32
    if floor_gbs is None:
        env = os.environ.get("COS_AUTOTUNE_FLOOR_GBS", "")
        floor_gbs = float(env) if env else 0.0

    def build(layers_plan):
        at = ({"schema": PLAN_SCHEMA, "layers": layers_plan}
              if layers_plan else False)
        return Net(net_param, state, dtype=dtype,
                   compute_dtype=compute_dtype, autotune=at)

    # bytes/layer follow the NET-WIDE dtype policy; per-layer variants
    # then override per layer inside the model
    act_b = 2 if (compute_dtype is not None
                  and jnp.dtype(compute_dtype) != jnp.dtype(dtype)) else 4

    def sleep_for(net, layers_plan):
        if not floor_gbs:
            return 0.0
        return rl.step_bytes_total(net, act_bytes=act_b,
                                   param_bytes=act_b,
                                   variants=layers_plan) \
            / (floor_gbs * 1e9)

    base_net = build({})
    params = base_net.init(jax.random.key(seed))
    inputs = _rand_inputs(base_net, seed)
    args = (params, inputs)
    step = _build_step(base_net, mode)
    base_sps, base_out = _measure(step, args, iters=measure_iters,
                                  warmup=warmup,
                                  sleep_s=sleep_for(base_net, {}))
    ref = _ref_values(base_out)

    # roofline ranking: only the top offenders are worth a compile
    rows = rl.classify(rl.analyze_net(base_net, act_bytes=act_b,
                                      param_bytes=act_b))
    by_name = {lp.name: lp for lp in base_net.compute_layers}
    ranked = [r for r in rows if r["type"] in TUNABLE_TYPES
              and r["layer"] in by_name][:max(1, top_layers)]

    plan_layers: Dict[str, dict] = {}
    per_layer: List[dict] = []
    best_sps = base_sps
    # best parity-passing variant per (type, bound) class, accepted or
    # not: a single layer's uplift (~1-2%) sits at the noise floor of
    # a short measurement, but composed across its whole class it can
    # be decisive — the generalize pass re-measures and gates the
    # composition, so seeding it from near-miss candidates is safe
    cand_win: Dict[Tuple[str, str], Tuple[float, dict]] = {}
    for row in ranked:
        lp = by_name[row["layer"]]
        for variant in legal_variants(base_net, lp, mode=mode):
            cand = dict(plan_layers)
            cand[lp.name] = variant
            try:
                net_v = build(cand)
                step_v = _build_step(net_v, mode)
                sps, out_v = _measure(
                    step_v, args, iters=measure_iters, warmup=warmup,
                    sleep_s=sleep_for(net_v, cand))
                par = _parity(ref, _ref_values(out_v))
            except Exception as e:  # noqa: BLE001 — an unbuildable
                #   variant loses the A/B, it must not kill the tune
                _LOG.warning("autotune: variant %s on %s failed: %s",
                             variant, lp.name, e)
                per_layer.append({"layer": lp.name, "type": lp.type,
                                  "bound": row["bound"],
                                  "variant": variant, "error": str(e),
                                  "accepted": False})
                continue
            accepted = (par <= tolerance
                        and sps >= best_sps * min_uplift)
            if par <= tolerance and sps > base_sps:
                ckey = (lp.type, row["bound"])
                if ckey not in cand_win or sps > cand_win[ckey][0]:
                    cand_win[ckey] = (sps, variant)
            per_layer.append({"layer": lp.name, "type": lp.type,
                              "bound": row["bound"], "variant": variant,
                              "steps_per_sec": round(sps, 4),
                              "uplift_vs_base": round(sps / base_sps, 4),
                              "parity_max_rel_diff": round(par, 6),
                              "accepted": accepted})
            if accepted:
                plan_layers[lp.name] = variant
                best_sps = sps

    # generalize winners across each (type, bound) class, then gate the
    # composed plan on one more measured A/B — never ship an unmeasured
    # composition.  Per-layer accepted winners take precedence; classes
    # with only near-miss candidates still get a shot, because the
    # composed measurement (not the noisy per-layer one) is the gate.
    generalized_from: Dict[str, str] = {}
    cls_win: Dict[Tuple[str, str], dict] = {}
    for row in ranked:
        v = plan_layers.get(row["layer"])
        if v:
            cls_win.setdefault((row["type"], row["bound"]), v)
    for ckey, (_, v) in cand_win.items():
        cls_win.setdefault(ckey, v)
    if generalize and cls_win:
        cand = dict(plan_layers)
        for r in rows:
            key = (r["type"], r["bound"])
            if key in cls_win and r["layer"] not in cand \
                    and r["layer"] in by_name:
                lp2 = by_name[r["layer"]]
                if cls_win[key] in legal_variants(base_net, lp2,
                                                 mode=mode):
                    cand[r["layer"]] = dict(cls_win[key])
                    generalized_from[r["layer"]] = "class"
        if len(cand) > len(plan_layers):
            try:
                net_g = build(cand)
                step_g = _build_step(net_g, mode)
                sps_g, out_g = _measure(
                    step_g, args, iters=measure_iters, warmup=warmup,
                    sleep_s=sleep_for(net_g, cand))
                par_g = _parity(ref, _ref_values(out_g))
                if par_g <= tolerance and sps_g >= max(
                        best_sps, base_sps * min_uplift):
                    plan_layers, best_sps = cand, sps_g
                else:
                    generalized_from = {}
            except Exception as e:  # noqa: BLE001 — see above
                _LOG.warning("autotune: generalized plan failed: %s", e)
                generalized_from = {}

    dg = net_digest(net_param)
    dk = device_kind()
    batch = base_net.input_specs[0][1][0] if base_net.input_specs else 0
    plan = {
        "schema": PLAN_SCHEMA,
        "version": PLAN_VERSION,
        "model_version": rl.MODEL_VERSION,
        "source": "tuned",
        "key": {
            "net_digest": dg,
            "device_kind": dk,
            "batch": int(batch),
            "dtype_policy": dtype_policy_str(dtype, compute_dtype),
            "mode": mode,
            "env": ambient_env(),
        },
        "tolerance": tolerance,
        "layers": plan_layers,
        "generalized": sorted(generalized_from),
        "measured": {
            "baseline_steps_per_sec": round(base_sps, 4),
            "tuned_steps_per_sec": round(best_sps, 4),
            "uplift": round(best_sps / base_sps, 4),
            "floor_gbs": floor_gbs,
            "measure_iters": measure_iters,
            "per_layer": per_layer,
        },
    }
    if save:
        path = save_plan(plan, None if cache_dir is None
                         else plan_cache_path(plan, cache_dir))
        _LOG.info("autotune: plan cached at %s (uplift %.2fx, %d "
                  "layer variants)", path, best_sps / base_sps,
                  len(plan_layers))
    return plan
