"""Layer forward functions, fillers, and Pallas kernels."""

from . import fillers, layers
from .layers import get_op, supported_types
