"""Caffe layer semantics as pure JAX functions (TPU-first).

Each layer type registers:
  * ``param_specs(lp, bottom_shapes)`` → list of (blob_name, shape, filler)
    for its learnable blobs (order == Caffe blob order, so `.caffemodel`
    import/export maps 1:1), and
  * ``apply(ctx, lp, params, bottoms)`` → list of top arrays.

Layout is Caffe-logical NCHW at layer boundaries; XLA's TPU layout
assignment maps convs/matmuls onto the MXU, so no manual NHWC plumbing is
needed for correctness, and compute-heavy paths stay fused under one jit.

Caffe behaviors reproduced (the "hard parts" of SURVEY.md §7):
  * pooling ceil-mode output sizing with tail-window clipping,
  * AVE pooling divisor = window ∩ padded region (not kernel area),
  * LRN ACROSS_CHANNELS uses alpha/local_size,
  * SoftmaxWithLoss VALID normalization + ignore_label,
  * Dropout inverted scaling at train time,
  * LSTM cont-gated recurrence (gate order i,f,o,g), time-major (T,B,·).

Reference equivalents: caffe-public layer implementations consumed via
`CaffeNet.cpp` (see SURVEY.md §2.5, §2.9 layer list).
"""

from __future__ import annotations

import contextlib
import math
import os
import zlib
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..proto.caffe import (EltwiseOp, FillerParameter, LayerParameter,
                           NormalizationMode, NormRegion, PoolMethod)

Array = jax.Array


@dataclass
class Ctx:
    """Per-call context threaded through layer application."""
    train: bool = False
    rng: Optional[Array] = None          # folded per-layer inside Net.apply
    state_in: Dict[str, List[Array]] = field(default_factory=dict)
    state_out: Dict[str, List[Array]] = field(default_factory=dict)
    layer_name: str = ""
    # LRN layer names whose op applies relu in-kernel (net.py's
    # COS_FUSE_RELU_LRN peephole)
    fused_relu_lrn: frozenset = frozenset()
    # this layer's autotune variant (per-layer precision/layout/fusion
    # plan entry, resolved ONCE at Net construction — ops must never
    # read env for these; None = no override, the inert default)
    variant: Optional[Dict] = None
    # conv-stem bias fusion (net.py peephole, generalized): conv layer
    # names whose bias add is deferred into the consuming LRN kernel,
    # and the LRN layer names that receive the bias as params[0]
    defer_bias: frozenset = frozenset()
    bias_lrn: frozenset = frozenset()
    # per-blob dequant scales for quantized-resident serving weights
    # ({layer: {blob: f32 scalar}}, serving/quant.py): an int8 weight
    # arriving at an op finds its max-abs scale here and runs the
    # dequant-free kernel path instead of quantizing per call
    qscales: Optional[Dict] = None

    def qscale(self, bname: str):
        if not self.qscales:
            return None
        return self.qscales.get(self.layer_name, {}).get(bname)

    def take_rng(self) -> Array:
        assert self.rng is not None, "layer needs rng but none provided"
        return jax.random.fold_in(self.rng, stable_hash(self.layer_name))

    def precision(self):
        """MXU precision pin for this layer's contractions: a layer the
        autotune plan holds at float32 computes at HIGHEST precision
        (the COS002 precision-floor discipline — an f32 variant that
        still multiplied in bf16 passes would be a lie); None
        otherwise (jax default)."""
        if self.variant and self.variant.get("dtype") == "float32":
            return jax.lax.Precision.HIGHEST
        return None


def stable_hash(name: str) -> int:
    """Process-independent name hash (Python's hash() is randomized per
    interpreter, which would break random_seed reproducibility)."""
    return zlib.crc32(name.encode("utf-8"))


_REGISTRY: Dict[str, "LayerOp"] = {}


@dataclass
class LayerOp:
    name: str
    apply: Callable
    param_specs: Callable = lambda lp, shapes: []
    is_loss: bool = False
    is_data: bool = False
    # layer updates running statistics in the forward pass and must run
    # in f32 (exempt from compute-dtype casts and rematerialization)
    f32_stats: bool = False


def register(name: str, *, params=None, is_loss=False, is_data=False,
             f32_stats=False):
    def deco(fn):
        _REGISTRY[name] = LayerOp(name, fn, params or (lambda lp, s: []),
                                  is_loss=is_loss, is_data=is_data,
                                  f32_stats=f32_stats)
        return fn
    return deco


def get_op(type_name: str) -> LayerOp:
    if type_name not in _REGISTRY:
        raise NotImplementedError(f"layer type {type_name!r} not supported")
    return _REGISTRY[type_name]


def supported_types() -> List[str]:
    return sorted(_REGISTRY)


def _filler(msg, default_type="constant") -> FillerParameter:
    if isinstance(msg, FillerParameter):
        return msg
    return FillerParameter(type=default_type)


# ---------------------------------------------------------------------------
# data layers — net inputs; shapes resolved by the net compiler
# ---------------------------------------------------------------------------

@register("MemoryData", is_data=True)
def _memory_data(ctx, lp, params, bottoms):
    raise RuntimeError("data layers are net inputs; never applied")


@register("CoSData", is_data=True)
def _cos_data(ctx, lp, params, bottoms):
    raise RuntimeError("data layers are net inputs; never applied")


@register("Input", is_data=True)
def _input(ctx, lp, params, bottoms):
    raise RuntimeError("data layers are net inputs; never applied")


@register("Data", is_data=True)
def _db_data(ctx, lp, params, bottoms):
    raise RuntimeError("data layers are net inputs; never applied")


@register("HDF5Data", is_data=True)
def _hdf5_data(ctx, lp, params, bottoms):
    raise RuntimeError("data layers are net inputs; never applied")


@register("DummyData", is_data=True)
def _dummy_data(ctx, lp, params, bottoms):
    raise RuntimeError("data layers are net inputs; never applied")


@register("ImageData", is_data=True)
def _image_data(ctx, lp, params, bottoms):
    raise RuntimeError("data layers are net inputs; never applied")


@register("HDF5Output")
def _hdf5_output(ctx, lp, params, bottoms):
    """hdf5_output_layer.cpp: an output sink — file I/O cannot live
    inside a jitted forward, so the bottoms are recorded in the forward
    state under 'hdf5_output:<name>' and the runtime writes them with
    `data.hdf5.write_hdf5_outputs` (see Net.apply's second return)."""
    ctx.state_out["hdf5_output:" + ctx.layer_name] = list(bottoms)
    return []


# ---------------------------------------------------------------------------
# Convolution / Deconvolution / InnerProduct / Embed
# ---------------------------------------------------------------------------

def _conv_geometry(cp):
    def pair(rep, h, w, default):
        if cp.has(h) or cp.has(w):
            if not (cp.has(h) and cp.has(w)):
                raise ValueError(f"{h} and {w} must be set together")
            return (int(getattr(cp, h)), int(getattr(cp, w)))
        v = getattr(cp, rep)
        if isinstance(v, list):
            if len(v) == 0:
                return (default, default)
            if len(v) == 1:
                return (int(v[0]), int(v[0]))
            return (int(v[0]), int(v[1]))
        return (int(v), int(v))

    kernel = pair("kernel_size", "kernel_h", "kernel_w", None)
    if kernel[0] is None:
        raise ValueError("convolution_param needs kernel_size or "
                         "kernel_h/kernel_w")
    stride = pair("stride", "stride_h", "stride_w", 1)
    pad = pair("pad", "pad_h", "pad_w", 0)
    dil = cp.dilation
    dilation = ((int(dil[0]), int(dil[-1] if len(dil) > 1 else dil[0]))
                if dil else (1, 1))
    return kernel, stride, pad, dilation


def _conv_params(lp, shapes):
    cp = lp.convolution_param
    (kh, kw), _, _, _ = _conv_geometry(cp)
    c_in = shapes[0][1]
    group = max(1, cp.group)
    specs = [("weight", (cp.num_output, c_in // group, kh, kw),
              _filler(cp.weight_filler if lp.convolution_param.has(
                  "weight_filler") else None))]
    if cp.bias_term:
        specs.append(("bias", (cp.num_output,),
                      _filler(cp.bias_filler if cp.has("bias_filler")
                              else None)))
    return specs


def _s2d_geometry_ok(c_in, cp, kh, kw, sh, sw, dh, dw) -> bool:
    """Geometric eligibility for the space-to-depth stem rewrite:
    C_in<=4, square stride>=2, no dilation, no groups.  Separated from
    the enable decision so the autotuner can both force the rewrite on
    a layer and enumerate it from blob shapes — ONE copy of the rule."""
    return (c_in <= 4 and sh == sw and sh >= 2
            and dh == dw == 1 and max(1, cp.group) == 1)


def _s2d_eligible(x, cp, kh, kw, sh, sw, dh, dw) -> bool:
    """Stem convs (C_in<=4, stride>=2) hit the MXU badly: the 8-lane
    channel padding and the strided 11x11/7x7 window waste most of the
    systolic array.  Space-to-depth by the stride factor rewrites them
    as dense stride-1 convs over C_in*s^2 channels — the standard TPU
    stem transform (MLPerf ResNet).  Same multiply-adds in a different
    summation order, so results match the direct conv to float-rounding
    tolerance, not bitwise (like any XLA layout change).  On by default
    on TPU; COS_CONV_S2D=0 forces the direct conv everywhere."""
    import os
    env = os.environ.get("COS_CONV_S2D")
    if env is not None:
        enabled = env == "1"
    else:
        from .pallas_kernels import pallas_enabled
        enabled = pallas_enabled()
    return enabled and _s2d_geometry_ok(x.shape[1], cp, kh, kw, sh, sw,
                                        dh, dw)


def _conv_layout() -> str:
    """COS_CONV_LAYOUT=NHWC requests NHWC-internal convolutions: the
    logical NCHW operands are transposed around an NHWC/HWIO conv.  XLA's
    transpose-folding absorbs the wrappers into the conv's dimension
    numbers, so the net effect is a layout *hint* — channels land on the
    minormost (lane) dimension without a layout-assignment round trip.
    A/B lever for the roofline experiments (docs/benchmarks.md); numerics
    are identical to float rounding.  Default NCHW."""
    import os
    return os.environ.get("COS_CONV_LAYOUT", "NCHW").upper()


def _nhwc_conv(x, w, strides, padding, rhs_dilation, groups,
               precision=None):
    """x (N,C,H,W), w (O,I/g,kh,kw) → NHWC-internal conv → (N,O,oh,ow)."""
    xt = x.transpose(0, 2, 3, 1)
    wt = w.transpose(2, 3, 1, 0)  # OIHW → HWIO
    out = lax.conv_general_dilated(
        xt, wt, window_strides=strides, padding=padding,
        rhs_dilation=rhs_dilation, feature_group_count=groups,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        precision=precision)
    return out.transpose(0, 3, 1, 2)


def _s2d_conv(x, w, s, kh, kw, ph, pw, precision=None):
    """stride-s conv as a stride-1 conv over s x s space-to-depth blocks.

    x: (N, C, H, W) already conceptually padded by (ph, pw) — padding is
    applied here together with the tail pad/crop to the block grid.
    w: (O, C, kh, kw).  Output identical to
    conv(x, w, stride=s, pad=(ph, pw))."""
    n, c, h, wd = x.shape
    o_h = (h + 2 * ph - kh) // s + 1
    o_w = (wd + 2 * pw - kw) // s + 1
    kb_h = (kh - 1) // s + 1
    kb_w = (kw - 1) // s + 1
    gh, gw = o_h + kb_h - 1, o_w + kb_w - 1
    # pad left with conv padding, right up/down to the block grid
    xt = jnp.pad(x, ((0, 0), (0, 0),
                     (ph, max(0, gh * s - h - ph)),
                     (pw, max(0, gw * s - wd - pw))))
    xt = xt[:, :, :gh * s, :gw * s]
    xt = xt.reshape(n, c, gh, s, gw, s).transpose(0, 1, 3, 5, 2, 4)
    xt = xt.reshape(n, c * s * s, gh, gw)
    oc = w.shape[0]
    wp = jnp.pad(w, ((0, 0), (0, 0),
                     (0, kb_h * s - kh), (0, kb_w * s - kw)))
    wp = wp.reshape(oc, c, kb_h, s, kb_w, s).transpose(0, 1, 3, 5, 2, 4)
    wp = wp.reshape(oc, c * s * s, kb_h, kb_w)
    return lax.conv_general_dilated(
        xt, wp, window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        precision=precision)


@register("Convolution", params=_conv_params)
def _conv(ctx, lp, params, bottoms):
    cp = lp.convolution_param
    (kh, kw), (sh, sw), (ph, pw), (dh, dw) = _conv_geometry(cp)
    x = bottoms[0]
    w = params[0]
    # per-layer autotune variant (resolved at Net construction) beats
    # the global env knobs; absent a variant the env behavior is
    # byte-identical to pre-autotune
    v = ctx.variant or {}
    layout = (v.get("layout") or "").lower()
    prec = ctx.precision()
    # no preferred_element_type: the TPU MXU accumulates in f32
    # internally either way, and forcing an f32 output breaks the
    # conv transpose (backward) for bf16 nets with a dtype mismatch
    if layout == "nhwc" or (not layout and _conv_layout() == "NHWC"):
        # NHWC experiment measures the plain conv, not the s2d rewrite —
        # one variable at a time (s2d is itself a layout transform).
        out = _nhwc_conv(x, w, (sh, sw), [(ph, ph), (pw, pw)],
                         (dh, dw), max(1, cp.group), precision=prec)
    elif (layout == "s2d"
          and _s2d_geometry_ok(x.shape[1], cp, kh, kw, sh, sw, dh, dw)) \
            or (not layout
                and _s2d_eligible(x, cp, kh, kw, sh, sw, dh, dw)):
        out = _s2d_conv(x, w, sh, kh, kw, ph, pw, precision=prec)
    else:
        out = lax.conv_general_dilated(
            x, w, window_strides=(sh, sw), padding=[(ph, ph), (pw, pw)],
            rhs_dilation=(dh, dw), feature_group_count=max(1, cp.group),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            precision=prec)
    if cp.bias_term and ctx.layer_name not in ctx.defer_bias:
        # defer_bias: the bias add (and relu+LRN) runs in the consuming
        # LRN layer's fused epilogue (net.py stem peephole)
        out = out + params[1].reshape(1, -1, 1, 1)
    return [out]


def _deconv_params(lp, shapes):
    cp = lp.convolution_param
    (kh, kw), _, _, _ = _conv_geometry(cp)
    c_in = shapes[0][1]
    group = max(1, cp.group)
    # Caffe Deconvolution weight blob: (C_in, N/group, kh, kw)
    specs = [("weight", (c_in, cp.num_output // group, kh, kw),
              _filler(cp.weight_filler if cp.has("weight_filler") else None))]
    if cp.bias_term:
        specs.append(("bias", (cp.num_output,),
                      _filler(cp.bias_filler if cp.has("bias_filler")
                              else None)))
    return specs


@register("Deconvolution", params=_deconv_params)
def _deconv(ctx, lp, params, bottoms):
    """Caffe deconv = gradient of conv wrt its input: output size
    s·(i−1) + k − 2p.  Expressed as an input-dilated convolution with a
    spatially flipped kernel and per-side padding (k−1−p), which XLA maps
    onto the MXU like any conv."""
    cp = lp.convolution_param
    (kh, kw), (sh, sw), (ph, pw), (dh, dw) = _conv_geometry(cp)
    x = bottoms[0]
    w = params[0]  # (C_in, C_out/g, kh, kw)
    g = max(1, cp.group)
    c_in = w.shape[0]
    c_out = w.shape[1] * g
    # (C_in, C_out/g, kh, kw) → (C_out, C_in/g, kh, kw), spatially flipped
    wk = w.reshape(g, c_in // g, c_out // g, kh, kw)
    wk = wk.transpose(0, 2, 1, 3, 4).reshape(c_out, c_in // g, kh, kw)
    wk = wk[:, :, ::-1, ::-1]
    ekh = (kh - 1) * dh + 1  # effective (dilated) kernel extent
    ekw = (kw - 1) * dw + 1
    out = lax.conv_general_dilated(
        x, wk, window_strides=(1, 1),
        padding=[(ekh - 1 - ph, ekh - 1 - ph), (ekw - 1 - pw, ekw - 1 - pw)],
        lhs_dilation=(sh, sw), rhs_dilation=(dh, dw),
        feature_group_count=g,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    if cp.bias_term:
        out = out + params[1].reshape(1, -1, 1, 1)
    return [out]


def _ip_params(lp, shapes):
    ip = lp.inner_product_param
    axis = ip.axis
    k = math.prod(shapes[0][axis:])
    shape = (k, ip.num_output) if ip.transpose else (ip.num_output, k)
    specs = [("weight", shape,
              _filler(ip.weight_filler if ip.has("weight_filler") else None))]
    if ip.bias_term:
        specs.append(("bias", (ip.num_output,),
                      _filler(ip.bias_filler if ip.has("bias_filler")
                              else None)))
    return specs


@register("InnerProduct", params=_ip_params)
def _inner_product(ctx, lp, params, bottoms):
    ip = lp.inner_product_param
    axis = ip.axis
    x = bottoms[0]
    lead = x.shape[:axis]
    x2 = x.reshape((math.prod(lead), -1))
    w = params[0]
    v = ctx.variant or {}
    if not ctx.train and w.dtype == jnp.int8:
        # quantized-RESIDENT serving weight (serving/quant.py): the
        # blob was quantized once at ModelRegistry.publish and lives
        # in HBM as the int8 operand itself — the kernel consumes it
        # with its cached max-abs scale, no per-call re-quantization
        from .pallas_kernels import int8_inner_product
        y = int8_inner_product(x2, w, transpose=bool(ip.transpose),
                               w_scale=ctx.qscale("weight"))
    elif v.get("int8") and not ctx.train:
        # quantized serving forward (autotune variant; TEST-phase nets
        # only — net.py refuses int8 on a TRAIN net): int8×int8 MXU
        # matmul on per-blob max-abs scales, int32 accumulation
        from .pallas_kernels import int8_inner_product
        y = int8_inner_product(x2, w, transpose=bool(ip.transpose))
    else:
        prec = ctx.precision()
        y = (jnp.matmul(x2, w, precision=prec) if ip.transpose
             else jnp.matmul(x2, w.T, precision=prec))
    if ip.bias_term:
        y = y + params[1]
    return [y.reshape(lead + (ip.num_output,))]


def _embed_params(lp, shapes):
    ep = lp.embed_param
    specs = [("weight", (ep.input_dim, ep.num_output),
              _filler(ep.weight_filler if ep.has("weight_filler") else None))]
    if ep.bias_term:
        specs.append(("bias", (ep.num_output,),
                      _filler(ep.bias_filler if ep.has("bias_filler")
                              else None)))
    return specs


@register("Embed", params=_embed_params)
def _embed(ctx, lp, params, bottoms):
    ep = lp.embed_param
    idx = bottoms[0].astype(jnp.int32)
    out = jnp.take(params[0], idx, axis=0)
    if ep.bias_term:
        out = out + params[1]
    return [out]


# ---------------------------------------------------------------------------
# Pooling (Caffe ceil-mode + divisor semantics)
# ---------------------------------------------------------------------------

def pool_output_dim(size: int, kernel: int, stride: int, pad: int) -> int:
    out = int(math.ceil((size + 2 * pad - kernel) / stride)) + 1
    if pad > 0 and (out - 1) * stride >= size + pad:
        out -= 1
    return out


@register("Pooling")
def _pooling(ctx, lp, params, bottoms):
    pp = lp.pooling_param
    x = bottoms[0]
    n, c, h, w = x.shape
    if pp.global_pooling:
        kh, kw = h, w
        sh = sw = 1
        ph = pw = 0
    else:
        for a, b in (("kernel_h", "kernel_w"), ("stride_h", "stride_w"),
                     ("pad_h", "pad_w")):
            if pp.has(a) != pp.has(b):
                raise ValueError(f"pooling_param: {a} and {b} must be set "
                                 "together")
        kh = int(pp.kernel_h) if pp.has("kernel_h") else int(pp.kernel_size)
        kw = int(pp.kernel_w) if pp.has("kernel_w") else int(pp.kernel_size)
        if kh == 0 or kw == 0:
            raise ValueError("pooling_param needs kernel_size or "
                             "kernel_h/kernel_w")
        sh = int(pp.stride_h) if pp.has("stride_h") else int(pp.stride)
        sw = int(pp.stride_w) if pp.has("stride_w") else int(pp.stride)
        ph = int(pp.pad_h) if pp.has("pad_h") else int(pp.pad)
        pw = int(pp.pad_w) if pp.has("pad_w") else int(pp.pad)
    oh = pool_output_dim(h, kh, sh, ph)
    ow = pool_output_dim(w, kw, sw, pw)
    # explicit asymmetric padding so the ceil-mode tail window exists
    eh = max(0, (oh - 1) * sh + kh - h - ph)
    ew = max(0, (ow - 1) * sw + kw - w - pw)
    if pp.pool == PoolMethod.MAX:
        xp = jnp.pad(x, ((0, 0), (0, 0), (ph, eh), (pw, ew)),
                     constant_values=-jnp.inf)
        out = lax.reduce_window(xp, -jnp.inf, lax.max,
                                (1, 1, kh, kw), (1, 1, sh, sw), "VALID")
    elif pp.pool == PoolMethod.AVE:
        xp = jnp.pad(x, ((0, 0), (0, 0), (ph, eh), (pw, ew)))
        s = lax.reduce_window(xp, 0.0, lax.add,
                              (1, 1, kh, kw), (1, 1, sh, sw), "VALID")
        # Caffe divisor: overlap of each window with the symmetric padded
        # region [0, size + 2*pad), NOT the raw kernel area
        ones_h = jnp.ones((1, 1, h + 2 * ph, 1), x.dtype)
        ones_w = jnp.ones((1, 1, 1, w + 2 * pw), x.dtype)
        ones_h = jnp.pad(ones_h, ((0, 0), (0, 0), (0, max(0, eh - ph)),
                                  (0, 0)))
        ones_w = jnp.pad(ones_w, ((0, 0), (0, 0), (0, 0),
                                  (0, max(0, ew - pw))))
        div_h = lax.reduce_window(ones_h, 0.0, lax.add, (1, 1, kh, 1),
                                  (1, 1, sh, 1), "VALID")
        div_w = lax.reduce_window(ones_w, 0.0, lax.add, (1, 1, 1, kw),
                                  (1, 1, 1, sw), "VALID")
        out = s / (div_h * div_w)
    elif pp.pool == PoolMethod.STOCHASTIC:
        # Caffe pooling_layer.cu PoolForward{Train,Test}: activations are
        # assumed non-negative (post-ReLU).  TRAIN samples one element per
        # window with probability value/sum(window); TEST outputs the
        # activation-weighted mean sum(a^2)/sum(a) (0 when the window sums
        # to 0).  Caffe forbids padding for STOCHASTIC (pooling_layer.cpp
        # SetUp check); zero padding is harmless here (zeros are never
        # sampled unless the whole window is zero).
        if ctx.train:
            patches = lax.conv_general_dilated_patches(
                x, (kh, kw), (sh, sw), [(ph, eh), (pw, ew)],
                dimension_numbers=("NCHW", "OIHW", "NCHW"))
            p = patches.reshape(n, c, kh * kw, oh, ow)
            # selection math in f32: in bf16 `u` can be exactly 0
            # (~2^-8) or cumsum can round below u*total, degenerating
            # argmax to index 0 and biasing sampling toward the
            # window's top-left element
            cum = jnp.cumsum(p.astype(jnp.float32), axis=2)
            total = cum[:, :, -1]        # Caffe accumulates, not re-sums
            u = jax.random.uniform(ctx.take_rng(), total.shape,
                                   dtype=jnp.float32, minval=1e-7,
                                   maxval=1.0)
            # first window index whose running sum crosses u * total
            idx = jnp.argmax(cum >= (u * total)[:, :, None], axis=2)
            out = jnp.take_along_axis(p, idx[:, :, None], axis=2)[:, :, 0]
        else:
            # weighted mean sum(a^2)/sum(a) via two reduce_windows — no
            # kh*kw patch materialization on the eval path
            xf = x.astype(jnp.float32)
            xp = jnp.pad(xf, ((0, 0), (0, 0), (ph, eh), (pw, ew)))
            total = lax.reduce_window(xp, 0.0, lax.add,
                                      (1, 1, kh, kw), (1, 1, sh, sw),
                                      "VALID")
            sq = lax.reduce_window(xp * xp, 0.0, lax.add,
                                   (1, 1, kh, kw), (1, 1, sh, sw),
                                   "VALID")
            out = jnp.where(total > 0, sq / jnp.where(total > 0, total, 1),
                            0.0).astype(x.dtype)
    else:
        raise NotImplementedError(f"pooling method {pp.pool}")
    return [out]


# ---------------------------------------------------------------------------
# elementwise activations
# ---------------------------------------------------------------------------

@register("ReLU")
def _relu(ctx, lp, params, bottoms):
    slope = lp.relu_param.negative_slope
    x = bottoms[0]
    if slope:
        return [jnp.where(x > 0, x, slope * x)]
    return [jax.nn.relu(x)]


def _prelu_params(lp, shapes):
    n = 1 if lp.prelu_param.channel_shared else shapes[0][1]
    f = (lp.prelu_param.filler if lp.prelu_param.has("filler")
         else FillerParameter(type="constant", value=0.25))
    return [("slope", (n,), f)]


@register("PReLU", params=_prelu_params)
def _prelu(ctx, lp, params, bottoms):
    x = bottoms[0]
    a = params[0].reshape((1, -1) + (1,) * (x.ndim - 2))
    return [jnp.where(x > 0, x, a * x)]


@register("ELU")
def _elu(ctx, lp, params, bottoms):
    a = lp.elu_param.alpha
    x = bottoms[0]
    return [jnp.where(x > 0, x, a * (jnp.exp(x) - 1.0))]


@register("Sigmoid")
def _sigmoid(ctx, lp, params, bottoms):
    return [jax.nn.sigmoid(bottoms[0])]


@register("TanH")
def _tanh(ctx, lp, params, bottoms):
    return [jnp.tanh(bottoms[0])]


@register("AbsVal")
def _absval(ctx, lp, params, bottoms):
    return [jnp.abs(bottoms[0])]


@register("BNLL")
def _bnll(ctx, lp, params, bottoms):
    x = bottoms[0]
    return [jnp.where(x > 0, x + jnp.log1p(jnp.exp(-x)),
                      jnp.log1p(jnp.exp(x)))]


@register("Power")
def _power(ctx, lp, params, bottoms):
    p = lp.power_param
    y = p.shift + p.scale * bottoms[0]
    if p.power != 1.0:
        y = jnp.power(y, p.power)
    return [y]


@register("Exp")
def _exp(ctx, lp, params, bottoms):
    p = lp.exp_param
    x = p.shift + p.scale * bottoms[0]
    if p.base > 0:
        return [jnp.power(p.base, x)]
    return [jnp.exp(x)]


@register("Log")
def _log(ctx, lp, params, bottoms):
    p = lp.log_param
    x = p.shift + p.scale * bottoms[0]
    y = jnp.log(x)
    if p.base > 0:
        y = y / math.log(p.base)
    return [y]


@register("Threshold")
def _threshold(ctx, lp, params, bottoms):
    t = lp.threshold_param.threshold
    return [(bottoms[0] > t).astype(bottoms[0].dtype)]


@register("Dropout")
def _dropout(ctx, lp, params, bottoms):
    ratio = lp.dropout_param.dropout_ratio
    x = bottoms[0]
    if not ctx.train or ratio == 0.0:
        return [x]
    keep = 1.0 - ratio
    mask = jax.random.bernoulli(ctx.take_rng(), keep, x.shape)
    return [jnp.where(mask, x / keep, 0.0)]


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------

@register("LRN")
def _lrn(ctx, lp, params, bottoms):
    p = lp.lrn_param
    x = bottoms[0]
    n = int(p.local_size)
    alpha, beta, k = p.alpha, p.beta, p.k
    # net.py's ReLU→LRN peephole routed the pre-activation here: apply
    # relu in-kernel (pallas) or inline (XLA fallback) — identical
    # semantics on every backend
    fuse_relu = lp.name in ctx.fused_relu_lrn
    if lp.name in ctx.bias_lrn:
        # generalized stem epilogue (net.py bias peephole): the
        # producing conv's bias arrives as params[0] and bias-add +
        # relu + LRN run in one fused pass (pallas on TPU, the
        # identical-semantics XLA chain elsewhere)
        from .pallas_kernels import (bias_relu_lrn_across_channels,
                                     pallas_enabled, xla_bias_relu_lrn)
        bias = params[0]
        if pallas_enabled() and x.ndim == 4:
            return [bias_relu_lrn_across_channels(x, bias, n, alpha,
                                                  beta, k)]
        return [xla_bias_relu_lrn(x, bias, n, alpha, beta, k)]
    if p.norm_region == NormRegion.ACROSS_CHANNELS:
        from .pallas_kernels import lrn_across_channels, pallas_enabled
        if pallas_enabled() and x.ndim == 4:
            # fused VMEM-resident kernel on TPU, with a matching fused
            # VJP kernel so the training path stays on Pallas
            return [lrn_across_channels(x, n, alpha, beta, k, False,
                                        fuse_relu)]
        if fuse_relu:
            x = jnp.maximum(x, 0)
        # one shared XLA fallback chain (pallas_kernels owns it so the
        # fused-epilogue fallback can never drift from this path)
        from .pallas_kernels import xla_lrn_across_channels
        return [xla_lrn_across_channels(x, n, alpha, beta, k)]
    else:  # WITHIN_CHANNEL: spatial window average of squares
        sq = x * x
        pad = n // 2
        sqp = jnp.pad(sq, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
        s = lax.reduce_window(sqp, 0.0, lax.add, (1, 1, n, n),
                              (1, 1, 1, 1), "VALID")
        scale = k + (alpha / (n * n)) * s
    return [x / jnp.power(scale, beta)]


@register("MVN")
def _mvn(ctx, lp, params, bottoms):
    p = lp.mvn_param
    x = bottoms[0]
    axes = (1, 2, 3) if p.across_channels else (2, 3)
    mean = jnp.mean(x, axis=axes, keepdims=True)
    y = x - mean
    if p.normalize_variance:
        var = jnp.mean(y * y, axis=axes, keepdims=True)
        y = y / (jnp.sqrt(var) + p.eps)
    return [y]


def _bn_params(lp, shapes):
    c = shapes[0][1]
    zero = FillerParameter(type="constant", value=0.0)
    return [("mean", (c,), zero), ("variance", (c,), zero),
            ("count", (1,), zero)]


@register("BatchNorm", params=_bn_params, f32_stats=True)
def _batch_norm(ctx, lp, params, bottoms):
    p = lp.batch_norm_param
    x = bottoms[0]
    eps = p.eps
    use_global = (p.use_global_stats if p.has("use_global_stats")
                  else not ctx.train)
    mean_b, var_b, count = params
    if use_global:
        scale = jnp.where(count[0] == 0, 1.0, 1.0 / count[0])
        mean = mean_b * scale
        var = var_b * scale
    else:
        axes = (0,) + tuple(range(2, x.ndim))
        mean = jnp.mean(x, axis=axes)
        var = jnp.mean(jnp.square(x), axis=axes) - jnp.square(mean)
        maf = p.moving_average_fraction
        # Caffe accumulates the UNBIASED variance into blobs_[1]
        # (batch_norm_layer.cpp bias_correction_factor m/(m-1),
        # m = elements per channel)
        m = x.shape[0] * math.prod(x.shape[2:])
        bias_corr = m / (m - 1.0) if m > 1 else 1.0
        ctx.state_out[ctx.layer_name] = [
            mean_b * maf + mean, var_b * maf + var * bias_corr,
            count * maf + 1.0]
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return [(x - mean.reshape(shape))
            / jnp.sqrt(var.reshape(shape) + eps)]


def _scale_params(lp, shapes):
    p = lp.scale_param
    if len(shapes) > 1:
        # two-bottom Scale: the multiplier IS bottom[1]; only an optional
        # bias blob is learnable (its shape follows bottom[1])
        if not p.bias_term:
            return []
        bf = (p.bias_filler if p.has("bias_filler")
              else FillerParameter(type="constant", value=0.0))
        return [("bias", tuple(shapes[1]), bf)]
    axis = p.axis if p.axis >= 0 else len(shapes[0]) + p.axis
    num_axes = p.num_axes
    if num_axes == -1:
        shape = shapes[0][axis:]
    else:
        shape = shapes[0][axis:axis + num_axes]
    f = p.filler if p.has("filler") else FillerParameter(type="constant",
                                                        value=1.0)
    specs = [("scale", tuple(shape), f)]
    if p.bias_term:
        bf = (p.bias_filler if p.has("bias_filler")
              else FillerParameter(type="constant", value=0.0))
        specs.append(("bias", tuple(shape), bf))
    return specs


@register("Scale", params=_scale_params)
def _scale(ctx, lp, params, bottoms):
    p = lp.scale_param
    x = bottoms[0]
    g = bottoms[1] if len(bottoms) > 1 else params[0]
    bias = None
    if p.bias_term:
        bias = params[0] if len(bottoms) > 1 else params[1]
    axis = p.axis if p.axis >= 0 else x.ndim + p.axis
    shape = [1] * x.ndim
    for i, d in enumerate(g.shape):
        shape[axis + i] = d
    y = x * g.reshape(shape)
    if bias is not None:
        y = y + bias.reshape(shape)
    return [y]


def _bias_params(lp, shapes):
    p = lp.bias_param
    axis = p.axis if p.axis >= 0 else len(shapes[0]) + p.axis
    if p.num_axes == -1:
        shape = shapes[0][axis:]
    else:
        shape = shapes[0][axis:axis + p.num_axes]
    f = p.filler if p.has("filler") else FillerParameter(type="constant")
    return [("bias", tuple(shape), f)]


@register("Bias", params=_bias_params)
def _bias(ctx, lp, params, bottoms):
    p = lp.bias_param
    x = bottoms[0]
    b = bottoms[1] if len(bottoms) > 1 else params[0]
    axis = p.axis if p.axis >= 0 else x.ndim + p.axis
    shape = [1] * x.ndim
    for i, d in enumerate(b.shape):
        shape[axis + i] = d
    return [x + b.reshape(shape)]


def _parameter_params(lp, shapes):
    shape = tuple(int(d) for d in lp.parameter_param.shape.dim)
    return [("param", shape, FillerParameter(type="constant"))]


@register("Parameter", params=_parameter_params)
def _parameter(ctx, lp, params, bottoms):
    """parameter_layer.hpp: the top IS a learnable blob of the given
    shape (lets arbitrary tensors be optimized, e.g. input embeddings)."""
    return [params[0]]


@register("BatchReindex")
def _batch_reindex(ctx, lp, params, bottoms):
    """batch_reindex_layer.cpp: top = bottom[0][bottom[1]] along axis 0
    (gather; gradients scatter-add back through the first bottom)."""
    x, idx = bottoms[0], bottoms[1]
    return [jnp.take(x, idx.astype(jnp.int32).reshape(-1), axis=0)]


@register("SPP")
def _spp(ctx, lp, params, bottoms):
    """Spatial pyramid pooling (spp_layer.cpp): for level i in
    [0, pyramid_height), pool into 2^i x 2^i bins, flatten each level
    and concat channel-wise → fixed-size vector regardless of input
    H, W.  Caffe's GetPoolingParam builds a per-level pooling layer
    with kernel = ceil(dim/bins), stride = kernel, and SYMMETRIC pad
    (remainder+1)/2 on both sides — delegated here to the Pooling
    layer so bin windows and the pooled-dim clip match bit-for-bit
    (weights ported from Caffe SPP nets reproduce)."""
    p = lp.spp_param
    x = bottoms[0]
    n, c, h, w = x.shape
    if not p.has("pyramid_height") or p.pyramid_height < 1:
        raise ValueError("spp_param.pyramid_height must be >= 1")
    if p.pool not in (PoolMethod.MAX, PoolMethod.AVE):
        raise NotImplementedError("SPP: MAX and AVE pooling only")
    outs = []
    for i in range(int(p.pyramid_height)):
        bins = 2 ** i
        kh = -(-h // bins)
        kw = -(-w // bins)
        pool_lp = LayerParameter(name=f"{lp.name}_level{i}",
                                 type="Pooling")
        pool_lp.pooling_param.pool = p.pool
        pool_lp.pooling_param.kernel_h = kh
        pool_lp.pooling_param.kernel_w = kw
        pool_lp.pooling_param.stride_h = kh
        pool_lp.pooling_param.stride_w = kw
        pool_lp.pooling_param.pad_h = (kh * bins - h + 1) // 2
        pool_lp.pooling_param.pad_w = (kw * bins - w + 1) // 2
        pooled = _pooling(ctx, pool_lp, [], [x])[0]
        outs.append(pooled.reshape(n, -1))
    return [jnp.concatenate(outs, axis=1)]


# ---------------------------------------------------------------------------
# shape ops
# ---------------------------------------------------------------------------

@register("Flatten")
def _flatten(ctx, lp, params, bottoms):
    p = lp.flatten_param
    x = bottoms[0]
    axis = p.axis if p.axis >= 0 else x.ndim + p.axis
    end = p.end_axis if p.end_axis >= 0 else x.ndim + p.end_axis
    shape = x.shape[:axis] + (-1,) + x.shape[end + 1:]
    return [x.reshape(shape)]


@register("Reshape")
def _reshape(ctx, lp, params, bottoms):
    p = lp.reshape_param
    x = bottoms[0]
    dims = list(p.shape.dim)
    axis = p.axis if p.axis >= 0 else x.ndim + p.axis
    num_axes = p.num_axes
    end = x.ndim if num_axes == -1 else axis + num_axes
    mid = []
    for i, d in enumerate(dims):
        if d == 0:
            mid.append(x.shape[axis + i])
        else:
            mid.append(int(d))
    shape = list(x.shape[:axis]) + mid + list(x.shape[end:])
    return [x.reshape(shape)]


@register("Concat")
def _concat(ctx, lp, params, bottoms):
    p = lp.concat_param
    axis = p.axis if p.has("axis") or not p.has("concat_dim") \
        else int(p.concat_dim)
    return [jnp.concatenate(bottoms, axis=axis)]


@register("Slice")
def _slice(ctx, lp, params, bottoms):
    p = lp.slice_param
    x = bottoms[0]
    axis = p.axis
    n_top = len(lp.top)
    if p.slice_point:
        points = [0] + [int(q) for q in p.slice_point] + [x.shape[axis]]
    else:
        if x.shape[axis] % n_top != 0:
            raise ValueError(
                f"Slice: axis size {x.shape[axis]} not divisible by "
                f"{n_top} tops (set slice_point explicitly)")
        step = x.shape[axis] // n_top
        points = [i * step for i in range(n_top + 1)]
    return [lax.slice_in_dim(x, points[i], points[i + 1], axis=axis)
            for i in range(n_top)]


@register("Eltwise")
def _eltwise(ctx, lp, params, bottoms):
    p = lp.eltwise_param
    op = p.operation
    if op == EltwiseOp.PROD:
        y = bottoms[0]
        for b in bottoms[1:]:
            y = y * b
    elif op == EltwiseOp.SUM:
        coeffs = p.coeff if p.coeff else [1.0] * len(bottoms)
        if len(coeffs) != len(bottoms):
            raise ValueError(
                f"Eltwise SUM: {len(coeffs)} coeffs for "
                f"{len(bottoms)} bottoms (must match)")
        y = coeffs[0] * bottoms[0]
        for c, b in zip(coeffs[1:], bottoms[1:]):
            y = y + c * b
    else:  # MAX
        y = bottoms[0]
        for b in bottoms[1:]:
            y = jnp.maximum(y, b)
    return [y]


@register("Tile")
def _tile(ctx, lp, params, bottoms):
    p = lp.tile_param
    x = bottoms[0]
    reps = [1] * x.ndim
    reps[p.axis] = int(p.tiles)
    return [jnp.tile(x, reps)]


@register("Reduction")
def _reduction(ctx, lp, params, bottoms):
    p = lp.reduction_param
    x = bottoms[0]
    axis = p.axis if p.axis >= 0 else x.ndim + p.axis
    flat = x.reshape(x.shape[:axis] + (-1,))
    op = p.operation
    if op == 1:
        y = jnp.sum(flat, axis=-1)
    elif op == 2:
        y = jnp.sum(jnp.abs(flat), axis=-1)
    elif op == 3:
        y = jnp.sum(flat * flat, axis=-1)
    else:
        y = jnp.mean(flat, axis=-1)
    return [p.coeff * y]


@register("Crop")
def _crop(ctx, lp, params, bottoms):
    p = lp.crop_param
    x, ref = bottoms
    axis = p.axis if p.axis >= 0 else x.ndim + p.axis
    offsets = list(p.offset) or [0]
    starts = [0] * x.ndim
    sizes = list(x.shape)
    for i in range(axis, x.ndim):
        off = offsets[i - axis] if i - axis < len(offsets) else offsets[-1]
        starts[i] = off
        sizes[i] = ref.shape[i]
    return [lax.dynamic_slice(x, starts, sizes)]


@register("Split")
def _split(ctx, lp, params, bottoms):
    return [bottoms[0] for _ in lp.top]


@register("Silence")
def _silence(ctx, lp, params, bottoms):
    return []


@register("ArgMax")
def _argmax(ctx, lp, params, bottoms):
    p = lp.argmax_param
    x = bottoms[0]
    k = int(p.top_k)
    if p.has("axis"):
        # keep the axis with size top_k; out_max_val selects values
        axis = p.axis if p.axis >= 0 else x.ndim + p.axis
        moved = jnp.moveaxis(x, axis, -1)
        vals, idxs = lax.top_k(moved, k)
        out = vals if p.out_max_val else idxs.astype(jnp.float32)
        return [jnp.moveaxis(out, -1, axis)]
    flat = x.reshape(x.shape[0], -1)
    vals, idxs = lax.top_k(flat, k)
    if p.out_max_val:
        return [jnp.stack([idxs.astype(jnp.float32), vals], axis=1)]
    return [idxs.astype(jnp.float32).reshape(x.shape[0], 1, k)]


# ---------------------------------------------------------------------------
# softmax / losses / metrics
# ---------------------------------------------------------------------------

@register("Softmax")
def _softmax(ctx, lp, params, bottoms):
    axis = lp.softmax_param.axis
    return [jax.nn.softmax(bottoms[0], axis=axis)]


def _loss_normalizer(norm_mode, valid_count, batch, full):
    if norm_mode == NormalizationMode.FULL:
        return full
    if norm_mode == NormalizationMode.BATCH_SIZE:
        return batch
    if norm_mode == NormalizationMode.NONE:
        return 1.0
    return jnp.maximum(valid_count, 1.0)  # VALID


@register("SoftmaxWithLoss", is_loss=True)
def _softmax_loss(ctx, lp, params, bottoms):
    axis = lp.softmax_param.axis if lp.has("softmax_param") else 1
    scores, labels = bottoms[0], bottoms[1]
    logp = jax.nn.log_softmax(scores, axis=axis)
    lbl = labels.astype(jnp.int32)
    # reshape labels to scores-without-class-axis
    outer = scores.shape[:axis]
    inner = scores.shape[axis + 1:]
    lbl = lbl.reshape(outer + inner)
    lp_msg = lp.loss_param
    has_ignore = lp.has("loss_param") and lp_msg.has("ignore_label")
    ignore = lp_msg.ignore_label if has_ignore else -1
    safe_lbl = jnp.where(lbl == ignore, 0, lbl) if has_ignore else lbl
    picked = jnp.take_along_axis(
        logp, jnp.expand_dims(safe_lbl, axis), axis=axis)
    nll = -jnp.squeeze(picked, axis)
    if has_ignore:
        mask = (lbl != ignore).astype(scores.dtype)
        nll = nll * mask
        valid = jnp.sum(mask)
    else:
        valid = float(math.prod(outer + inner))
    # legacy loss_param.normalize: true → VALID, false → BATCH_SIZE
    # (only consulted when 'normalization' itself is unset)
    if lp.has("loss_param") and not lp_msg.has("normalization") \
            and lp_msg.has("normalize"):
        norm_mode = (NormalizationMode.VALID if lp_msg.normalize
                     else NormalizationMode.BATCH_SIZE)
    elif lp.has("loss_param"):
        norm_mode = lp_msg.normalization
    else:
        norm_mode = NormalizationMode.VALID
    denom = _loss_normalizer(norm_mode, valid, scores.shape[0],
                             math.prod(outer + inner))
    return [jnp.sum(nll) / denom]


@register("EuclideanLoss", is_loss=True)
def _euclidean_loss(ctx, lp, params, bottoms):
    a, b = bottoms[0], bottoms[1]
    diff = a - b
    return [jnp.sum(diff * diff) / (2.0 * a.shape[0])]


@register("SigmoidCrossEntropyLoss", is_loss=True)
def _sce_loss(ctx, lp, params, bottoms):
    x, t = bottoms[0], bottoms[1]
    # stable: max(x,0) - x*t + log(1+exp(-|x|))
    loss = jnp.maximum(x, 0) - x * t + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return [jnp.sum(loss) / x.shape[0]]


@register("ContrastiveLoss", is_loss=True)
def _contrastive_loss(ctx, lp, params, bottoms):
    """Siamese-net loss (contrastive_loss_layer.cpp): bottoms are two
    feature batches a, b (N, C) and a pair label y (1 = similar).
    loss = 1/(2N) Σ [ y·d² + (1−y)·max(margin − d, 0)² ], d = ‖a−b‖;
    legacy_version uses max(margin − d², 0) instead."""
    p = lp.contrastive_loss_param
    a, b, y = bottoms[0], bottoms[1], bottoms[2]
    n = a.shape[0]
    y = y.reshape(n).astype(a.dtype)
    diff = (a - b).reshape(n, -1)
    dist_sq = jnp.sum(diff * diff, axis=1)
    if p.legacy_version:
        mismatch = jnp.maximum(p.margin - dist_sq, 0.0)
    else:
        # sqrt guard: d=0 has zero gradient through maximum anyway
        d = jnp.sqrt(jnp.maximum(dist_sq, 1e-12))
        m = jnp.maximum(p.margin - d, 0.0)
        mismatch = m * m
    return [jnp.sum(y * dist_sq + (1.0 - y) * mismatch) / (2.0 * n)]


@register("HingeLoss", is_loss=True)
def _hinge_loss(ctx, lp, params, bottoms):
    x, y = bottoms[0], bottoms[1]
    n = x.shape[0]
    lbl = y.astype(jnp.int32).reshape(n)
    sign = jnp.ones_like(x).at[jnp.arange(n), lbl].set(-1.0)
    margin = jnp.maximum(0.0, 1.0 + sign * x)
    if lp.hinge_loss_param.norm == 2:
        return [jnp.sum(margin * margin) / n]
    return [jnp.sum(margin) / n]


@register("MultinomialLogisticLoss", is_loss=True)
def _mll_loss(ctx, lp, params, bottoms):
    """-log(p[label]) on an already-softmaxed bottom (legacy pairing of
    Softmax + MultinomialLogisticLoss)."""
    probs, labels = bottoms[0], bottoms[1]
    n = probs.shape[0]
    lbl = labels.astype(jnp.int32).reshape(n)
    p = probs.reshape(n, -1)[jnp.arange(n), lbl]
    return [-jnp.sum(jnp.log(jnp.maximum(p, 1e-20))) / n]


@register("InfogainLoss", is_loss=True)
def _infogain_loss(ctx, lp, params, bottoms):
    """Infogain-weighted multinomial loss: -(1/N) Σ_n Σ_k H[label_n, k]
    · log(p_nk).  The infogain matrix H arrives as bottom[2] (or, in
    Caffe, from infogain_loss_param.source — supply it as a bottom
    here; H = identity degenerates to MultinomialLogisticLoss)."""
    probs, labels = bottoms[0], bottoms[1]
    n, k = probs.shape[0], probs.reshape(probs.shape[0], -1).shape[1]
    if len(bottoms) > 2:
        h = bottoms[2].reshape(k, k)
    elif lp.has("infogain_loss_param") \
            and lp.infogain_loss_param.source:
        # load H from the binaryproto at trace time (constant in the
        # compiled program) — the standard Caffe configuration
        import numpy as _np
        from ..proto.caffe import BlobProto
        with open(lp.infogain_loss_param.source, "rb") as f:
            bp = BlobProto.from_binary(f.read())
        h = jnp.asarray(_np.asarray(bp.data, _np.float32).reshape(k, k))
    else:
        h = jnp.eye(k, dtype=probs.dtype)
    lbl = labels.astype(jnp.int32).reshape(n)
    logp = jnp.log(jnp.maximum(probs.reshape(n, k), 1e-20))
    rows = h[lbl]                       # (N, K) infogain row per sample
    return [-jnp.sum(rows * logp) / n]


@register("Accuracy")
def _accuracy(ctx, lp, params, bottoms):
    p = lp.accuracy_param
    axis = p.axis
    k = int(p.top_k)
    scores, labels = bottoms[0], bottoms[1]
    outer = scores.shape[:axis]
    inner = scores.shape[axis + 1:]
    lbl = labels.astype(jnp.int32).reshape(outer + inner)
    has_ignore = lp.has("accuracy_param") and p.has("ignore_label")
    moved = jnp.moveaxis(scores, axis, -1)
    if k == 1:
        correct = (jnp.argmax(moved, axis=-1) == lbl)
    else:
        _, topi = lax.top_k(moved, k)
        correct = jnp.any(topi == lbl[..., None], axis=-1)
    correct = correct.astype(scores.dtype)
    if has_ignore:
        mask = (lbl != p.ignore_label).astype(scores.dtype)
        return [jnp.sum(correct * mask) / jnp.maximum(jnp.sum(mask), 1.0)]
    return [jnp.mean(correct)]


# ---------------------------------------------------------------------------
# attention (extension: long-context, time-major like the recurrent layers)
# ---------------------------------------------------------------------------

def _mha_params(lp, shapes):
    ap = lp.attention_param
    d_model = math.prod(shapes[0][2:]) if len(shapes[0]) > 2 else 1
    h = int(ap.num_heads)
    hd = int(ap.head_dim)
    wf = _filler(ap.weight_filler if ap.has("weight_filler") else None,
                 "xavier")
    return [("W_qkv", (3 * h * hd, d_model), wf),
            ("W_o", (d_model, h * hd), wf)]


_FLASH_SUPPRESS = 0      # >0 while tracing a multi-device SPMD step
_FLASH_MESH: list = []   # (mesh, batch_axes, head_axes, time_axes)


@contextlib.contextmanager
def suppress_flash():
    """Disable the flash-attention dispatch for the duration — an
    explicit opt-out for callers (and tests) that need the einsum
    path regardless of backend; ParallelSolver itself now always
    installs the flash_mesh route on multi-device meshes."""
    global _FLASH_SUPPRESS
    _FLASH_SUPPRESS += 1
    try:
        yield
    finally:
        _FLASH_SUPPRESS -= 1


@contextlib.contextmanager
def flash_mesh(mesh, batch_axes=("dp",), head_axes=("tp",),
               time_axes=("sp",)):
    """Route the flash dispatch through shard_map over `mesh` for the
    duration of a trace.  Attention is embarrassingly parallel over
    batch x heads, so each device runs the kernel on its (B/dp, H/tp)
    local block; when the mesh also shards TIME (sp axis), the body is
    the differentiable fused RING (parallel.sp._ring_attention_local)
    — K/V shards rotate on ppermute while flash kernels accumulate —
    so prototxt-driven sequence-parallel training gets ring+flash
    without hand-rolled steps."""
    _FLASH_MESH.append((mesh, tuple(batch_axes), tuple(head_axes),
                        tuple(time_axes)))
    try:
        yield
    finally:
        _FLASH_MESH.pop()


def _flash_interpret() -> bool:
    """COS_FLASH_INTERPRET=1 forces the Pallas kernels in interpret
    mode on any backend — how the CPU suite exercises the shard_map
    flash route on virtual meshes."""
    return os.environ.get("COS_FLASH_INTERPRET") == "1"


def _attention_dispatch(q, k, v, *, causal: bool):
    """Flash (Pallas, O(block·T) VMEM) on TPU when the shape tiles;
    under a multi-device mesh the kernel runs per-device via shard_map
    over (batch, heads); XLA einsum attention otherwise — numerically
    the same math (tests/test_pallas.py flash parity)."""
    from .pallas_kernels import flash_attention, pallas_enabled
    t = q.shape[2]
    interpret = _flash_interpret()
    # only 128-aligned sequence lengths take the kernel: Mosaic block
    # shapes must tile (8, 128), and at small T the O(T²) XLA path is
    # cheap anyway
    enabled = ((pallas_enabled() or interpret) and not _FLASH_SUPPRESS
               and not os.environ.get("COS_DISABLE_FLASH"))
    if enabled and _FLASH_MESH:
        import functools
        from jax.sharding import PartitionSpec as P
        from ..parallel.sp import shard_map_nocheck
        mesh, b_axes, h_axes, t_axes = _FLASH_MESH[-1]
        shape = dict(mesh.shape)
        b_axes = tuple(a for a in b_axes if shape.get(a, 1) > 1)
        h_axes = tuple(a for a in h_axes if shape.get(a, 1) > 1)
        t_axes = tuple(a for a in t_axes if shape.get(a, 1) > 1)
        nb = math.prod(shape[a] for a in b_axes) if b_axes else 1
        nh = math.prod(shape[a] for a in h_axes) if h_axes else 1
        tiles = q.shape[0] % nb == 0 and q.shape[1] % nh == 0
        if t_axes and len(t_axes) == 1 and tiles and t % shape[t_axes[0]] == 0:
            # TIME sharded: differentiable fused ring per (b, h) block
            nt = shape[t_axes[0]]
            from ..parallel.sp import flash_block_size
            if flash_block_size(t // nt) is not None:
                from ..parallel.sp import _ring_attention_local
                spec = P(b_axes or None, h_axes or None, t_axes, None)
                fl = shard_map_nocheck(
                    functools.partial(
                        _ring_attention_local, axis_name=t_axes[0],
                        causal=causal,
                        flash="interpret" if interpret else True),
                    mesh, (spec, spec, spec), spec)
                return fl(q, k, v)
            # local T unsuited to the kernel: einsum path below
        elif not t_axes and tiles and t % 128 == 0:
            spec = P(b_axes or None, h_axes or None, None, None)
            fl = shard_map_nocheck(
                functools.partial(flash_attention, causal=causal,
                                  block_q=128, block_k=128,
                                  interpret=interpret),
                mesh, (spec, spec, spec), spec)
            return fl(q, k, v)
        # shapes don't tile the mesh: einsum path below
    elif enabled and not _FLASH_MESH and t % 128 == 0:
        return flash_attention(q, k, v, causal, 128, 128,
                               interpret=interpret)
    from ..parallel.sp import attention as _plain_attention
    return _plain_attention(q, k, v, causal=causal)


@register("MultiHeadAttention", params=_mha_params)
def _mha(ctx, lp, params, bottoms):
    """Multi-head self-attention on time-major (T, B, D) input —
    extension beyond the reference (SURVEY §5.7: it has no attention at
    all).  Under jit on a mesh, GSPMD partitions the attention einsums
    along whatever axes the activations carry; for explicit
    sequence-parallel ring execution use `parallel.sp.ring_attention`
    (same math, shard_map + ppermute) in a hand-rolled step."""
    ap = lp.attention_param
    x = bottoms[0]
    t_steps, batch = x.shape[0], x.shape[1]
    h, hd = int(ap.num_heads), int(ap.head_dim)
    xf = x.reshape(t_steps, batch, -1)
    qkv = jnp.einsum("tbd,ed->tbe", xf, params[0])
    qkv = qkv.reshape(t_steps, batch, 3, h, hd)
    # (B, H, T, hd)
    q, k, v = (jnp.moveaxis(qkv[:, :, i], (0, 1, 2), (2, 0, 1))
               for i in range(3))
    var = ctx.variant or {}
    if var.get("attention") == "reference":
        # autotune variant: pin the einsum reference path (A/B partner
        # of the flash dispatch; same math, see tests/test_pallas.py)
        with suppress_flash():
            o = _attention_dispatch(q, k, v, causal=bool(ap.causal))
    else:
        o = _attention_dispatch(q, k, v, causal=bool(ap.causal))
    # back to (T, B, H*hd)
    o = jnp.moveaxis(o, (0, 1, 2), (1, 2, 0)).reshape(t_steps, batch,
                                                      h * hd)
    return [jnp.einsum("tbe,de->tbd", o, params[1])]


def _moe_params(lp, shapes):
    mp = lp.moe_param
    d = int(shapes[0][-1])
    e = int(mp.num_experts)
    h = int(mp.hidden_dim)
    if mp.has("weight_filler"):
        wf = _filler(mp.weight_filler)
        return [("router", (d, e), wf), ("W1", (e, d, h), wf),
                ("W2", (e, h, d), wf)]
    # explicit xavier-equivalent uniform bounds: the generic fan
    # heuristic (fan_in = count/shape[0]) misreads these layouts —
    # router is (in, out) and W1/W2 carry a leading expert dim
    def unif(fan_in):
        s = math.sqrt(3.0 / fan_in)
        return FillerParameter(type="uniform", min=-s, max=s)

    return [("router", (d, e), unif(d)), ("W1", (e, d, h), unif(d)),
            ("W2", (e, h, d), unif(h))]


@register("MixtureOfExperts", params=_moe_params)
def _moe(ctx, lp, params, bottoms):
    """Top-k routed expert FFN on (..., D) input — extension beyond the
    reference, built the way TPU MoE stacks are (Switch/GShard-style
    fixed expert capacity):

    * each token's top-k experts get it IF the expert still has room;
      capacity C = ceil(k·N/E · capacity_factor) is a static shape, so
      the dispatch is a scatter into a dense (E, C, D) buffer (mode
      'drop' discards overflow) and the expert FFN is two expert-major
      batched matmuls that shard over the `ep` mesh axis under GSPMD
      (`parallel.dp.tp_param_specs`) — memory O(E·C·D), not O(E·N·D);
    * gates come from the softmax router (normalized over the chosen k
      for k>1), so routing stays differentiable through the combine;
    * if the layer declares a second top it emits the load-balancing
      auxiliary loss  E · Σ_e f_e·P_e  (f = realized assignment
      fraction, P = mean router probability) — weight it with the
      layer's second `loss_weight`.
    """
    mp = lp.moe_param
    router, w1, w2 = params
    x = bottoms[0]
    lead = x.shape[:-1]
    d = x.shape[-1]
    e = int(mp.num_experts)
    k = max(1, int(mp.top_k))
    xf = x.reshape(-1, d)                       # (N, D) tokens
    n = xf.shape[0]
    cap = max(1, int(math.ceil(k * n / e * float(mp.capacity_factor))))

    logits = xf @ router                        # (N, E)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    topv, topi = lax.top_k(probs, k)            # (N, k)
    gates = topv / topv.sum(-1, keepdims=True) if k > 1 else topv

    # slot-major flattening: every token's 1st choice claims capacity
    # before any token's 2nd choice (GShard dispatch order)
    flat_e = topi.T.reshape(-1)                 # (k·N,)
    onehot = jax.nn.one_hot(flat_e, e, dtype=jnp.float32)
    # position of each assignment within its expert's buffer — int32
    # accumulation: a float32 cumsum is exact only to 2^24, beyond
    # which positions silently collide and corrupt capacity accounting
    ionehot = onehot.astype(jnp.int32)
    pos = jnp.cumsum(ionehot, axis=0) - 1
    pos = jnp.sum(pos * ionehot, axis=-1)       # (k·N,) int32
    keep = pos < cap

    tokens = jnp.tile(xf, (k, 1))               # (k·N, D) slot-major
    disp = jnp.zeros((e, cap, d), x.dtype).at[flat_e, pos].set(
        tokens, mode="drop")                    # overflow dropped
    hidden = jax.nn.relu(jnp.einsum("ecd,edh->ech", disp, w1))
    out = jnp.einsum("ech,ehd->ecd", hidden, w2)

    gathered = out[flat_e, jnp.minimum(pos, cap - 1)]       # (k·N, D)
    gathered = jnp.where(keep[:, None], gathered, 0.0)
    gf = (gates.T.reshape(-1)[:, None].astype(x.dtype) * gathered)
    combined = gf.reshape(k, n, d).sum(axis=0)
    tops = [combined.reshape(lead + (d,))]

    if len(lp.top) > 1:
        # Switch-Transformer balance loss: realized assignment
        # fraction × mean router prob, scaled by E (=1 at uniform)
        frac = onehot.mean(axis=0)              # (E,)
        mean_p = probs.mean(axis=0)
        tops.append((e * jnp.sum(frac * mean_p)).astype(jnp.float32))
    return tops


# ---------------------------------------------------------------------------
# recurrent layers (time-major (T, B, ·), cont-gated — Caffe RecurrentLayer)
# ---------------------------------------------------------------------------

def _lstm_params(lp, shapes):
    rp = lp.recurrent_param
    n = int(rp.num_output)
    d = math.prod(shapes[0][2:]) if len(shapes[0]) > 2 else 1
    wf = _filler(rp.weight_filler if rp.has("weight_filler") else None)
    bf = _filler(rp.bias_filler if rp.has("bias_filler") else None)
    specs = [("W_xc", (4 * n, d), wf), ("b_c", (4 * n,), bf),
             ("W_hc", (4 * n, n), wf)]
    # bottoms: x, cont[, x_static][, c_0, h_0 (expose_hidden)]
    n_state = 2 if rp.expose_hidden else 0
    if len(shapes) - n_state > 2:  # static input bottom present
        ds = math.prod(shapes[2][1:])
        specs.append(("W_xc_static", (4 * n, ds), wf))
    return specs


@register("LSTM", params=_lstm_params)
def _lstm(ctx, lp, params, bottoms):
    """Caffe LSTMLayer: x (T,B,D), cont (T,B) in {0,1}; gate order i,f,o,g;
    cont gates both h_{t-1} and c_{t-1} (sequence restart ⇒ zero state).
    Time loop is a `lax.scan` — XLA compiles one fused step, the MXU sees
    a (B,D)x(D,4N) matmul per step; the big x-projection for ALL steps is
    hoisted out of the scan as one (T*B,D)x(D,4N) matmul.

    expose_hidden: bottoms gain [h_0, c_0] ((1,B,N) or (B,N)) after any
    static input; tops gain [h_T, c_T] — Caffe's LSTMLayer orders the
    recurrent blobs h-first (RecurrentInputBlobNames) — enabling chunked
    sequences and O(T) incremental decoding."""
    rp = lp.recurrent_param
    n = int(rp.num_output)
    expose = bool(rp.expose_hidden)
    x, cont = bottoms[0], bottoms[1]
    t_steps, batch = x.shape[0], x.shape[1]
    xf = x.reshape(t_steps, batch, -1)
    w_xc, b_c, w_hc = params[0], params[1], params[2]
    has_static = len(params) > 3
    # hoisted input projection: one big MXU matmul over all timesteps
    xproj = jnp.einsum("tbd,gd->tbg", xf, w_xc) + b_c
    if has_static:
        xproj = xproj + (bottoms[2].reshape(batch, -1) @ params[3].T)

    cont_f = cont.reshape(t_steps, batch, 1).astype(xf.dtype)

    def step(carry, inp):
        h_prev, c_prev = carry
        xp_t, cont_t = inp
        h_g = h_prev * cont_t
        c_g = c_prev * cont_t
        gates = xp_t + h_g @ w_hc.T
        i, f, o, g = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        o = jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c = f * c_g + i * g
        h = o * jnp.tanh(c)
        return (h, c), h

    if expose:
        si = 2 + (1 if has_static else 0)
        h0 = bottoms[si].reshape(batch, n).astype(xf.dtype)
        c0 = bottoms[si + 1].reshape(batch, n).astype(xf.dtype)
    else:
        h0 = jnp.zeros((batch, n), xf.dtype)
        c0 = jnp.zeros((batch, n), xf.dtype)
    (h_t, c_t), hs = lax.scan(step, (h0, c0), (xproj, cont_f))
    if expose:
        return [hs, h_t.reshape(1, batch, n), c_t.reshape(1, batch, n)]
    return [hs]


def _rnn_params(lp, shapes):
    rp = lp.recurrent_param
    n = int(rp.num_output)
    d = math.prod(shapes[0][2:]) if len(shapes[0]) > 2 else 1
    wf = _filler(rp.weight_filler if rp.has("weight_filler") else None)
    bf = _filler(rp.bias_filler if rp.has("bias_filler") else None)
    return [("W_xh", (n, d), wf), ("b_h", (n,), bf), ("W_hh", (n, n), wf),
            ("W_ho", (n, n), wf), ("b_o", (n,), bf)]


@register("RNN", params=_rnn_params)
def _rnn(ctx, lp, params, bottoms):
    """Caffe RNNLayer: h_t = tanh(W_hh h'_{t-1} + W_xh x_t + b_h);
    o_t = tanh(W_ho h_t + b_o)."""
    rp = lp.recurrent_param
    n = int(rp.num_output)
    x, cont = bottoms[0], bottoms[1]
    t_steps, batch = x.shape[0], x.shape[1]
    xf = x.reshape(t_steps, batch, -1)
    w_xh, b_h, w_hh, w_ho, b_o = params
    xproj = jnp.einsum("tbd,nd->tbn", xf, w_xh) + b_h
    cont_f = cont.reshape(t_steps, batch, 1).astype(xf.dtype)

    def step(h_prev, inp):
        xp_t, cont_t = inp
        h = jnp.tanh(xp_t + (h_prev * cont_t) @ w_hh.T)
        o = jnp.tanh(h @ w_ho.T + b_o)
        return h, o

    h0 = jnp.zeros((batch, n), xf.dtype)
    _, os = lax.scan(step, h0, (xproj, cont_f))
    return [os]
