"""Pallas TPU kernels for ops XLA doesn't fuse optimally.

LRN ACROSS_CHANNELS (CaffeNet norm1/norm2 hot path): XLA lowers the
reduce_window over channels to a separate pass over HBM; the Pallas
kernel keeps each (C, spatial-tile) block resident in VMEM and computes
square → 5-wide channel-window sum (static shifted adds on the VPU) →
pow → divide in one fused pass, one HBM read + one write per element.

`lrn_across_channels(x, ...)` pads the flattened spatial dim to the
128-lane grid, runs the kernel per (batch, tile), and is used by
`ops.layers._lrn` when running on TPU (fallback: the XLA reduce_window
path — numerically identical, see tests/test_pallas.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE = 512  # spatial lanes per block (4 × 128)


def _lrn_kernel(x_ref, o_ref, *, local_size: int, alpha: float,
                beta: float, k: float):
    x = x_ref[0]                     # (C, TILE) resident in VMEM
    sq = x * x
    c = x.shape[0]
    pad = local_size // 2
    acc = sq
    for off in range(1, pad + 1):
        # shift down: channel i accumulates channel i-off
        down = jnp.concatenate(
            [jnp.zeros((off, sq.shape[1]), sq.dtype), sq[:-off]], axis=0)
        up = jnp.concatenate(
            [sq[off:], jnp.zeros((off, sq.shape[1]), sq.dtype)], axis=0)
        acc = acc + down + up
    scale = k + (alpha / local_size) * acc
    o_ref[0] = x * jnp.exp(-beta * jnp.log(scale))


def lrn_across_channels(x: jax.Array, *, local_size: int = 5,
                        alpha: float = 1e-4, beta: float = 0.75,
                        k: float = 1.0,
                        interpret: bool = False) -> jax.Array:
    """(N, C, H, W) float32 → LRN, Caffe semantics (alpha/local_size)."""
    n, c, h, w = x.shape
    hw = h * w
    padded = (hw + TILE - 1) // TILE * TILE
    xf = x.reshape(n, c, hw)
    if padded != hw:
        xf = jnp.pad(xf, ((0, 0), (0, 0), (0, padded - hw)))
    kern = functools.partial(_lrn_kernel, local_size=local_size,
                             alpha=alpha, beta=beta, k=k)
    out = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((n, c, padded), x.dtype),
        grid=(n, padded // TILE),
        in_specs=[pl.BlockSpec((1, c, TILE),
                               lambda i, j: (i, 0, j),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, c, TILE), lambda i, j: (i, 0, j),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(xf)
    return out[:, :, :hw].reshape(n, c, h, w)


def pallas_enabled() -> bool:
    """Pallas kernels activate on real TPU backends only (CPU tests use
    interpret=True explicitly)."""
    import os
    if os.environ.get("COS_DISABLE_PALLAS"):
        return False
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False
