"""Pallas TPU kernels for ops XLA doesn't fuse optimally.

LRN ACROSS_CHANNELS (CaffeNet norm1/norm2 hot path): XLA lowers the
reduce_window over channels to a separate pass over HBM; the Pallas
kernel keeps each (C, spatial-tile) block resident in VMEM and computes
square → 5-wide channel-window sum (static shifted adds on the VPU) →
pow → divide in one fused pass, one HBM read + one write per element.

`lrn_across_channels(x, ...)` pads the flattened spatial dim to the
128-lane grid, runs the kernel per (batch, tile), and is used by
`ops.layers._lrn` when running on TPU (fallback: the XLA reduce_window
path — numerically identical, see tests/test_pallas.py).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE = 512  # spatial lanes per block (4 × 128)


def _window_sum(v: jax.Array, pad: int) -> jax.Array:
    """Σ over the symmetric channel window via static shifted adds (VPU)."""
    acc = v
    for off in range(1, pad + 1):
        down = jnp.concatenate(
            [jnp.zeros((off, v.shape[1]), v.dtype), v[:-off]], axis=0)
        up = jnp.concatenate(
            [v[off:], jnp.zeros((off, v.shape[1]), v.dtype)], axis=0)
        acc = acc + down + up
    return acc


def _lrn_kernel_fwd_only(x_ref, o_ref, *, local_size: int, alpha: float,
                         beta: float, k: float, fuse_relu: bool):
    """The one forward kernel (train AND eval): no scale residual.
    The backward kernel recomputes the denominators from x — a few VPU
    ops on a block already resident in VMEM — instead of storing an
    activation-sized scale tensor (round-5 perf pass: dropping the
    residual removes one full-size HBM write on the forward and one
    read on the backward, ~2/7 of the LRN stage's training traffic).

    Math runs in f32 regardless of the I/O dtype: in mixed (bf16)
    training, scale = 1 + (α/n)·Σx² computed in bf16 (eps ≈ 8e-3)
    rounds away most of the normalizer's significant digits.  The
    upcast lives in VMEM, so HBM traffic is unchanged.

    fuse_relu computes lrn(max(x, 0)) on the pre-activation input:
    XLA cannot fuse a producer into an opaque pallas call, so a
    separate ReLU→LRN chain materializes BOTH the relu output (the
    kernel's residual) and — for the relu mask — keeps the
    pre-activation live too.  Fused, the only residual is the
    pre-activation x and the mask is recomputed in VMEM (net.py's
    relu+lrn peephole, COS_FUSE_RELU_LRN)."""
    x = x_ref[0].astype(jnp.float32)
    if fuse_relu:
        x = jnp.maximum(x, 0.0)
    pad = local_size // 2
    scale = k + (alpha / local_size) * _window_sum(x * x, pad)
    o_ref[0] = (x * jnp.exp(-beta * jnp.log(scale))).astype(o_ref.dtype)


def _lrn_bwd_kernel(x_ref, dy_ref, dx_ref, *, local_size: int,
                    alpha: float, beta: float, k: float,
                    fuse_relu: bool):
    """dx = dy·s^{-β} − (2αβ/n)·x·Σ_{i∈W} dy_i·x_i·s_i^{-β-1}, with
    s recomputed in-VMEM from x in f32 (bit-identical to the
    forward's: same block, same op order, same upcast).  With
    fuse_relu the LRN gradient flows through max(x,0) and the mask
    zeroes dx where x < 0 — also recomputed in VMEM."""
    xr = x_ref[0].astype(jnp.float32)
    dy = dy_ref[0].astype(jnp.float32)
    x = jnp.maximum(xr, 0.0) if fuse_relu else xr
    pad = local_size // 2
    s = k + (alpha / local_size) * _window_sum(x * x, pad)
    s_nb = jnp.exp(-beta * jnp.log(s))        # s^{-β}
    u = dy * x * s_nb / s                      # dy·x·s^{-β-1}
    dx = dy * s_nb - (2.0 * alpha * beta / local_size) * x \
        * _window_sum(u, pad)
    if fuse_relu:
        dx = jnp.where(xr > 0.0, dx, 0.0)
    dx_ref[0] = dx.astype(dx_ref.dtype)


def _pad_flat(x):
    n, c, h, w = x.shape
    hw = h * w
    padded = (hw + TILE - 1) // TILE * TILE
    xf = x.reshape(n, c, hw)
    if padded != hw:
        xf = jnp.pad(xf, ((0, 0), (0, 0), (0, padded - hw)))
    return xf, hw, padded


def _block_spec(c):
    return pl.BlockSpec((1, c, TILE), lambda i, j: (i, 0, j),
                        memory_space=pltpu.VMEM)


def _lrn_fwd_call(x, local_size, alpha, beta, k, interpret, fuse_relu):
    n, c, h, w = x.shape
    xf, hw, padded = _pad_flat(x)
    kern = functools.partial(_lrn_kernel_fwd_only, local_size=local_size,
                             alpha=alpha, beta=beta, k=k,
                             fuse_relu=fuse_relu)
    out = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((n, c, padded), x.dtype),
        grid=(n, padded // TILE),
        in_specs=[_block_spec(c)],
        out_specs=_block_spec(c),
        interpret=interpret,
    )(xf)
    return out[:, :, :hw].reshape(n, c, h, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6))
def lrn_across_channels(x: jax.Array, local_size: int = 5,
                        alpha: float = 1e-4, beta: float = 0.75,
                        k: float = 1.0,
                        interpret: bool = False,
                        fuse_relu: bool = False) -> jax.Array:
    """(N, C, H, W) → LRN, Caffe semantics (alpha/local_size); with
    fuse_relu, lrn(relu(x)) in one pass (see the kernel docstring).
    Differentiable: a second fused kernel computes the exact VJP,
    recomputing the denominators (and relu mask) in VMEM from the
    saved input — the only residual is x itself, so training adds
    zero extra HBM traffic over inference."""
    return _lrn_fwd_call(x, local_size, alpha, beta, k, interpret,
                         fuse_relu)


def _lrn_vjp_fwd(x, local_size, alpha, beta, k, interpret, fuse_relu):
    out = _lrn_fwd_call(x, local_size, alpha, beta, k, interpret,
                        fuse_relu)
    return out, x


def _lrn_vjp_bwd(local_size, alpha, beta, k, interpret, fuse_relu, res,
                 dy):
    x = res
    n, c, h, w = x.shape
    xf, hw, padded = _pad_flat(x)
    dyf, _, _ = _pad_flat(dy)
    kern = functools.partial(_lrn_bwd_kernel, local_size=local_size,
                             alpha=alpha, beta=beta, k=k,
                             fuse_relu=fuse_relu)
    dx = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((x.shape[0], c, padded), x.dtype),
        grid=(x.shape[0], padded // TILE),
        in_specs=[_block_spec(c), _block_spec(c)],
        out_specs=_block_spec(c),
        interpret=interpret,
    )(xf, dyf)
    return (dx[:, :, :hw].reshape(n, c, h, w),)


lrn_across_channels.defvjp(_lrn_vjp_fwd, _lrn_vjp_bwd)


# ---------------------------------------------------------------------------
# Fused conv-stem epilogue: bias + ReLU + LRN in one VMEM pass
# ---------------------------------------------------------------------------
# Generalizes the fuse_relu LRN kernel one producer further: the conv's
# per-channel bias add joins relu+lrn in the epilogue, so the conv can
# emit its RAW matmul output and the stem chain conv→(+bias)→relu→lrn
# costs one HBM read + one write per element instead of materializing
# the biased pre-activation as the kernel's residual.  Backward parity
# follows the existing kernel's design: the VJP kernel recomputes the
# biased input, the relu mask, and the normalizers in VMEM from the
# saved RAW x + bias; d_bias is the channel-sum of d_x (exact — the
# bias add is an affine shift), reduced in XLA where it fuses.

def _lrn_kernel_fwd_bias(x_ref, b_ref, o_ref, *, local_size: int,
                         alpha: float, beta: float, k: float):
    """lrn(relu(x + bias)) — the bias_relu epilogue forward.  Math in
    f32 in VMEM regardless of I/O dtype (see _lrn_kernel_fwd_only)."""
    x = x_ref[0].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    x = jnp.maximum(x, 0.0)
    pad = local_size // 2
    scale = k + (alpha / local_size) * _window_sum(x * x, pad)
    o_ref[0] = (x * jnp.exp(-beta * jnp.log(scale))).astype(o_ref.dtype)


def _lrn_bwd_kernel_bias(x_ref, b_ref, dy_ref, dx_ref, *,
                         local_size: int, alpha: float, beta: float,
                         k: float):
    """d/d(x) of lrn(relu(x + bias)): the _lrn_bwd_kernel math on the
    recomputed biased input, masked where x + bias < 0.  The returned
    dx is ALSO d/d(x + bias), so the caller derives d_bias as its
    (N, H, W) channel sum."""
    xr = x_ref[0].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    dy = dy_ref[0].astype(jnp.float32)
    x = jnp.maximum(xr, 0.0)
    pad = local_size // 2
    s = k + (alpha / local_size) * _window_sum(x * x, pad)
    s_nb = jnp.exp(-beta * jnp.log(s))        # s^{-β}
    u = dy * x * s_nb / s                      # dy·x·s^{-β-1}
    dx = dy * s_nb - (2.0 * alpha * beta / local_size) * x \
        * _window_sum(u, pad)
    dx = jnp.where(xr > 0.0, dx, 0.0)
    dx_ref[0] = dx.astype(dx_ref.dtype)


def _bias_spec(c):
    return pl.BlockSpec((c, 1), lambda i, j: (0, 0),
                        memory_space=pltpu.VMEM)


def _bias_col(bias):
    # (C,) → (C, 1) f32 column: broadcasts against the (C, TILE) block
    return bias.astype(jnp.float32).reshape(-1, 1)


def _bias_lrn_fwd_call(x, bias, local_size, alpha, beta, k, interpret):
    n, c, h, w = x.shape
    xf, hw, padded = _pad_flat(x)
    kern = functools.partial(_lrn_kernel_fwd_bias, local_size=local_size,
                             alpha=alpha, beta=beta, k=k)
    out = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((n, c, padded), x.dtype),
        grid=(n, padded // TILE),
        in_specs=[_block_spec(c), _bias_spec(c)],
        out_specs=_block_spec(c),
        interpret=interpret,
    )(xf, _bias_col(bias))
    return out[:, :, :hw].reshape(n, c, h, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def bias_relu_lrn_across_channels(x: jax.Array, bias: jax.Array,
                                  local_size: int = 5,
                                  alpha: float = 1e-4,
                                  beta: float = 0.75, k: float = 1.0,
                                  interpret: bool = False) -> jax.Array:
    """(N, C, H, W) raw conv output + (C,) bias → lrn(relu(x + bias)),
    Caffe LRN semantics, one fused pass.  Differentiable in x AND bias:
    the VJP kernel recomputes bias-add, relu mask and normalizers in
    VMEM (residuals: the raw x and the (C,) bias — no biased
    pre-activation is ever materialized in HBM)."""
    return _bias_lrn_fwd_call(x, bias, local_size, alpha, beta, k,
                              interpret)


def _bias_lrn_vjp_fwd(x, bias, local_size, alpha, beta, k, interpret):
    out = _bias_lrn_fwd_call(x, bias, local_size, alpha, beta, k,
                             interpret)
    return out, (x, bias)


def _bias_lrn_vjp_bwd(local_size, alpha, beta, k, interpret, res, dy):
    x, bias = res
    n, c, h, w = x.shape
    xf, hw, padded = _pad_flat(x)
    dyf, _, _ = _pad_flat(dy)
    kern = functools.partial(_lrn_bwd_kernel_bias, local_size=local_size,
                             alpha=alpha, beta=beta, k=k)
    dx = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((n, c, padded), x.dtype),
        grid=(n, padded // TILE),
        in_specs=[_block_spec(c), _bias_spec(c), _block_spec(c)],
        out_specs=_block_spec(c),
        interpret=interpret,
    )(xf, _bias_col(bias), dyf)
    dx = dx[:, :, :hw].reshape(n, c, h, w)
    # the padded tail lanes of dx are exact zeros (dy padding), so the
    # channel sum over the CROPPED dx is the exact d_bias
    db = jnp.sum(dx.astype(jnp.float32), axis=(0, 2, 3)).astype(
        bias.dtype)
    return dx, db


bias_relu_lrn_across_channels.defvjp(_bias_lrn_vjp_fwd,
                                     _bias_lrn_vjp_bwd)


def xla_lrn_across_channels(x, local_size, alpha, beta, k):
    """THE XLA across-channels LRN fallback chain (square → channel
    reduce_window → scale → divide) — one copy shared by
    ops.layers._lrn's off-TPU path and the fused-epilogue fallback
    below, so a numerics fix can never land in one and miss the
    other."""
    from jax import lax
    sq = x * x
    pad = local_size // 2
    sqp = jnp.pad(sq, ((0, 0), (pad, pad), (0, 0), (0, 0)))
    s = lax.reduce_window(sqp, 0.0, lax.add, (1, local_size, 1, 1),
                          (1, 1, 1, 1), "VALID")
    scale = k + (alpha / local_size) * s
    return x / jnp.power(scale, beta)


def xla_bias_relu_lrn(x, bias, local_size, alpha, beta, k):
    """Reference/fallback path for the fused stem epilogue — identical
    semantics on every backend (ops.layers._lrn routes here off-TPU)."""
    x = jnp.maximum(x + bias.reshape(1, -1, 1, 1).astype(x.dtype), 0)
    return xla_lrn_across_channels(x, local_size, alpha, beta, k)


# ---------------------------------------------------------------------------
# int8 forward matmul (serving InnerProduct)
# ---------------------------------------------------------------------------
# The quantized-serving down payment (ROADMAP item 3): InnerProduct
# forward as an int8×int8 MXU matmul with int32 accumulation, weights
# and activations on per-blob max-abs scales — the exact scale
# machinery gradsync's int8 wire uses (parallel/gradsync.quantize_int8,
# round-to-nearest here: inference wants determinism, not unbiased
# accumulation).  int8 quarters the weight HBM read and doubles MXU
# issue rate on chips with int8 MXU paths; accuracy drift is gated by
# the autotuner's pinned parity tolerance before the variant is chosen.

INT8_BLOCK_M = 32          # int8 min sublane tile
INT8_BLOCK_N = 128
INT8_BLOCK_LANE = 128      # K must tile the 128-lane dimension


def _int8_matmul_kernel(x_ref, w_ref, o_ref):
    # (bm, K) int8 · (bn, K) int8 → (bm, bn) int32 on the MXU
    o_ref[...] = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)


def int8_matmul(xq: jax.Array, wq: jax.Array, *,
                interpret: bool = False) -> jax.Array:
    """(M, K) int8 @ (N, K) int8ᵀ → (M, N) int32.  Pallas-tiled when
    the shapes tile (grid over M/N blocks, K resident per block — the
    flash kernels' layout); XLA int8 dot_general otherwise (same
    int32-accumulated math on every backend, incl. CPU)."""
    m, kk = xq.shape
    n = wq.shape[0]
    tiles = (m % INT8_BLOCK_M == 0 and n % INT8_BLOCK_N == 0
             and kk % INT8_BLOCK_LANE == 0)
    if tiles and (interpret or pallas_enabled()):
        xspec = pl.BlockSpec((INT8_BLOCK_M, kk), lambda i, j: (i, 0),
                             memory_space=pltpu.VMEM)
        wspec = pl.BlockSpec((INT8_BLOCK_N, kk), lambda i, j: (j, 0),
                             memory_space=pltpu.VMEM)
        ospec = pl.BlockSpec((INT8_BLOCK_M, INT8_BLOCK_N),
                             lambda i, j: (i, j),
                             memory_space=pltpu.VMEM)
        return pl.pallas_call(
            _int8_matmul_kernel,
            out_shape=jax.ShapeDtypeStruct((m, n), jnp.int32),
            grid=(m // INT8_BLOCK_M, n // INT8_BLOCK_N),
            in_specs=[xspec, wspec],
            out_specs=ospec,
            interpret=interpret,
        )(xq, wq)
    return jax.lax.dot_general(xq, wq, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.int32)


def int8_inner_product(x: jax.Array, w: jax.Array, *,
                       transpose: bool = False,
                       interpret: bool = False,
                       w_scale: Optional[jax.Array] = None
                       ) -> jax.Array:
    """Quantized InnerProduct forward: y ≈ x @ wᵀ (Caffe layout; or
    x @ w when `transpose`), both operands on per-blob max-abs int8
    scales, int32 accumulation, output in x's dtype.  Forward-only —
    the serving path; training never routes here.

    Two weight regimes:

      * `w` float, `w_scale` None — the autotune-variant path: the
        weight quantizes INSIDE the traced forward, an O(N·K)
        abs-max+round paid on every flush.  The autotuner's A/B
        measures the variant WITH this cost, so a net where
        re-quantization eats the matmul win never selects int8.
      * `w` already int8 with its publish-time `w_scale` — the
        quantized-RESIDENT path (serving/quant.py): the model was
        quantized ONCE at ModelRegistry.publish and the resident blob
        IS the MXU operand, so the per-call weight quantization above
        disappears; only the activation still quantizes per call
        (it must — its values change per request)."""
    from ..parallel.gradsync import quantize_int8
    wn = w.T if transpose else w              # (N, K)
    xq, sx = quantize_int8(x, None)
    if wn.dtype == jnp.int8:
        if w_scale is None:
            raise ValueError(
                "int8_inner_product: pre-quantized int8 weight needs "
                "its publish-time w_scale (serving/quant.py)")
        wqn, sw = wn, w_scale
    else:
        wqn, sw = quantize_int8(wn, None)
    acc = int8_matmul(xq, wqn, interpret=interpret)
    return (acc.astype(jnp.float32) * (sx * sw)).astype(x.dtype)


def pallas_enabled() -> bool:
    """Pallas kernels activate on real TPU backends only (CPU tests use
    interpret=True explicitly)."""
    import os
    if os.environ.get("COS_DISABLE_PALLAS"):
        return False
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Flash attention (blockwise online-softmax), fwd + bwd kernels
# ---------------------------------------------------------------------------
# The MultiHeadAttention hot path: XLA materializes the (T, T) score
# matrix in HBM for both passes; these kernels keep one (block_q, T)
# strip of scores in VMEM and stream K/V blocks past it (the standard
# flash decomposition: running max m, normalizer l, f32 accumulator).
# Memory: O(block·T) VMEM instead of O(T²) HBM — within a device this
# is the same trick ring attention plays across devices (parallel/sp.py
# accumulate(), same m/l/corr algebra), so the two compose: ring over
# device shards, flash within a shard.
#
# Layout: q,k,v (B, H, T, D) flattened to (B·H, T, D); grid =
# (B·H, T/block).  K/V block specs expose the full (T, D) per head —
# VMEM-bounded at T·D·4 bytes ≈ 4 MB at T=8k, D=128 f32 (longer
# sequences belong to ring attention's shards anyway).

BLOCK_Q = 128
BLOCK_K = 128
_NEG_INF = -1e30          # finite mask value: -inf NaNs the m-corr path


def _online_softmax_step(q, kb, vb, m, l, acc, *, sm_scale: float,
                         causal: bool, q_pos, k_pos):
    """One online-softmax accumulation (the flash/ring shared algebra):
    scores for (q, kb) fold into the (m, l, acc) carry.  The m_safe
    guard makes fully-masked-so-far rows accumulate exact zeros (a
    no-op for rows that have seen the causal diagonal).  m and l are
    (block_q, 1) column vectors — Mosaic's block-shape rule wants the
    per-row stats rank-2, and the column form broadcasts against the
    (block_q, block_k) score strip with no reshapes."""
    s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * sm_scale
    if causal:
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    m_safe = jnp.where(m_new <= _NEG_INF * 0.5, 0.0, m_new)
    p = jnp.exp(s - m_safe)
    corr = jnp.exp(m - m_safe)
    l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * corr + jnp.dot(
        p, vb, preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                      sm_scale: float, causal: bool, block_k: int):
    q = q_ref[0].astype(jnp.float32)            # (block_q, D)
    t = k_ref.shape[1]
    block_q = q.shape[0]
    qi = pl.program_id(1)
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(i, carry):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        k_pos = i * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        return _online_softmax_step(q, kb, vb, m, l, acc,
                                    sm_scale=sm_scale, causal=causal,
                                    q_pos=q_pos, k_pos=k_pos)

    if causal:
        # K/V blocks starting past this q block's last row are fully
        # masked — skipping them halves the causal pass's work
        n_k = ((qi + 1) * block_q + block_k - 1) // block_k
    else:
        n_k = t // block_k
    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    a0 = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_k, body, (m0, l0, a0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref,
                          delta_ref, dk_ref, dv_ref, *, sm_scale: float,
                          causal: bool, block_q: int):
    kb = k_ref[0].astype(jnp.float32)           # (block_k, D)
    vb = v_ref[0].astype(jnp.float32)
    t = q_ref.shape[1]
    block_k = kb.shape[0]
    ki = pl.program_id(1)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)

    def body(i, carry):
        dk, dv = carry
        qb = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        dob = do_ref[0, pl.ds(i * block_q, block_q), :].astype(
            jnp.float32)
        lse = lse_ref[0, pl.ds(i * block_q, block_q)]   # (block_q, 1)
        dlt = delta_ref[0, pl.ds(i * block_q, block_q)]
        s = jnp.dot(qb, kb.T,
                    preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)                     # exact probabilities
        dv_new = dv + jnp.dot(p.T, dob,
                              preferred_element_type=jnp.float32)
        dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - dlt) * sm_scale
        dk_new = dk + jnp.dot(ds.T, qb,
                              preferred_element_type=jnp.float32)
        return dk_new, dv_new

    z = jnp.zeros((block_k, kb.shape[-1]), jnp.float32)
    # causal: q blocks ending before this k block's first row see only
    # masked scores — start at the diagonal
    i0 = (ki * block_k) // block_q if causal else 0
    dk, dv = jax.lax.fori_loop(i0, t // block_q, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref,
                         delta_ref, dq_ref, *, sm_scale: float,
                         causal: bool, block_k: int):
    qb = q_ref[0].astype(jnp.float32)            # (block_q, D)
    dob = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]                             # (block_q, 1)
    dlt = delta_ref[0]
    t = k_ref.shape[1]
    block_q = qb.shape[0]
    qi = pl.program_id(1)
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(i, dq):
        kb = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(qb, kb.T,
                    preferred_element_type=jnp.float32) * sm_scale
        if causal:
            k_pos = i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - dlt) * sm_scale
        return dq + jnp.dot(ds, kb, preferred_element_type=jnp.float32)

    if causal:
        n_k = ((qi + 1) * block_q + block_k - 1) // block_k
    else:
        n_k = t // block_k
    dq = jax.lax.fori_loop(0, n_k, body,
                           jnp.zeros((block_q, qb.shape[-1]),
                                     jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_specs(block, d, t):
    # `*_` absorbs the scalar-prefetch refs appended to index-map args
    # when these specs are used under a PrefetchScalarGridSpec.
    # Per-row stats (m/l/lse/delta) travel as (bh, t, 1) column vectors:
    # Mosaic requires the last two block dims divisible by (8, 128) OR
    # equal to the array dims — (block, 1) satisfies that ((1, block)
    # from a rank-2 (bh, t) layout does not, and fails to lower).
    qspec = pl.BlockSpec((1, block, d), lambda b, i, *_: (b, i, 0))
    kvspec = pl.BlockSpec((1, t, d), lambda b, i, *_: (b, 0, 0))
    vec = pl.BlockSpec((1, block, 1), lambda b, i, *_: (b, i, 0))
    vec_full = pl.BlockSpec((1, t, 1), lambda b, i, *_: (b, 0, 0))
    return qspec, kvspec, vec, vec_full


def _flash_fwd_call(q, k, v, sm_scale, causal, block_q, block_k,
                    interpret):
    bh, t, d = q.shape
    if t % block_q or t % block_k:
        # a truncated grid would leave the output/lse tail rows
        # uninitialized garbage — fail loudly (mirrors
        # flash_block_update; in-repo callers pre-check and fall back
        # to the XLA path, this guards direct calls)
        raise ValueError(
            f"flash_attention needs T divisible by the blocks: "
            f"t={t} % block_q={block_q}, t={t} % block_k={block_k}")
    kern = functools.partial(_flash_fwd_kernel, sm_scale=sm_scale,
                             causal=causal, block_k=block_k)
    qspec, kvspec, vec, _ = _flash_specs(block_q, d, t)
    out, lse = pl.pallas_call(
        kern,
        out_shape=(jax.ShapeDtypeStruct((bh, t, d), q.dtype),
                   jax.ShapeDtypeStruct((bh, t, 1), jnp.float32)),
        grid=(bh, t // block_q),
        in_specs=[qspec, kvspec, kvspec],
        out_specs=(qspec, vec),
        interpret=interpret,
    )(q, k, v)
    return out, lse[:, :, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False, block_q: int = BLOCK_Q,
                    block_k: int = BLOCK_K,
                    interpret: bool = False) -> jax.Array:
    """Fused blockwise attention, (B, H, T, D) → (B, H, T, D).

    Same math as parallel.sp.attention (softmax(QKᵀ/√D)V, optional
    causal mask); O(block·T) VMEM instead of an O(T²) HBM score
    matrix, exact (not approximate) via online softmax.  Requires T
    divisible by the block sizes — callers fall back to the XLA path
    otherwise (ops.layers._mha)."""
    b, h, t, d = q.shape
    sm_scale = 1.0 / math.sqrt(d)
    qf, kf, vf = (x.reshape(b * h, t, d) for x in (q, k, v))
    out, _ = _flash_fwd_call(qf, kf, vf, sm_scale, causal, block_q,
                             block_k, interpret)
    return out.reshape(b, h, t, d)


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k, interpret):
    b, h, t, d = q.shape
    sm_scale = 1.0 / math.sqrt(d)
    qf, kf, vf = (x.reshape(b * h, t, d) for x in (q, k, v))
    out, lse = _flash_fwd_call(qf, kf, vf, sm_scale, causal, block_q,
                               block_k, interpret)
    return out.reshape(b, h, t, d), (qf, kf, vf, out, lse)


def flash_bwd_block(qf, kf, vf, dof, lse, delta, *, causal: bool,
                    block_q: int, block_k: int, interpret: bool,
                    out_dtype=None):
    """dq, dk, dv for one (q-group, kv-block) attention pair from the
    saved stats — the flash backward building block.  All operands
    flattened (B·H, T, D) / (B·H, T); `causal` masks with LOCAL
    positions, so callers composing cross-shard pairs (ring backward,
    parallel/sp.py) pass causal=True only for the diagonal pair and
    causal=False for fully-visible ones.  `out_dtype` overrides the
    gradient dtype — accumulating callers pass float32 so bf16 inputs
    don't round each per-hop partial before the sum."""
    bh, t, d = qf.shape
    sm_scale = 1.0 / math.sqrt(d)
    lse = lse[:, :, None]          # (bh, t, 1): see _flash_specs
    delta = delta[:, :, None]
    qspec, kvspec, vec, vec_full = _flash_specs(block_q, d, t)
    kspec_b, _, _, _ = _flash_specs(block_k, d, t)
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, sm_scale=sm_scale,
                          causal=causal, block_k=block_k),
        out_shape=jax.ShapeDtypeStruct((bh, t, d),
                                       out_dtype or qf.dtype),
        grid=(bh, t // block_q),
        in_specs=[qspec, kvspec, kvspec, qspec, vec, vec],
        out_specs=qspec,
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, sm_scale=sm_scale,
                          causal=causal, block_q=block_q),
        out_shape=(jax.ShapeDtypeStruct((bh, t, d),
                                        out_dtype or kf.dtype),
                   jax.ShapeDtypeStruct((bh, t, d),
                                        out_dtype or vf.dtype)),
        grid=(bh, t // block_k),
        in_specs=[kvspec, kspec_b, kspec_b, kvspec, vec_full, vec_full],
        out_specs=(kspec_b, kspec_b),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)
    return dq, dk, dv


def _flash_vjp_bwd(causal, block_q, block_k, interpret, res, do):
    qf, kf, vf, out, lse = res
    bh, t, d = qf.shape
    dof = do.reshape(bh, t, d)
    # delta = rowsum(dO ∘ O): cheap elementwise+reduce, XLA fuses it
    delta = jnp.sum(dof.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)
    dq, dk, dv = flash_bwd_block(qf, kf, vf, dof, lse, delta,
                                 causal=causal, block_q=block_q,
                                 block_k=block_k, interpret=interpret)
    shape = do.shape
    return (dq.reshape(shape), dk.reshape(shape), dv.reshape(shape))


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# ---------------------------------------------------------------------------
# Flash block-update: the ring-attention inner step as a fused kernel
# ---------------------------------------------------------------------------
# parallel/sp.py's ring rotates K/V shards around the ICI ring and
# accumulates each incoming block with the same online-softmax algebra
# the flash kernels use (m/l/corr).  Inside shard_map the code is
# per-device, so a pallas_call is legal (no GSPMD partitioning of an
# opaque call) — this kernel fuses one accumulate() step: VMEM-resident
# score strip instead of a (T_local, T_local) HBM matrix per ring hop.
# The ring is differentiable end to end: parallel/sp.py's
# _make_ring_flash wraps this forward with a custom VJP whose backward
# is a second ring pass over flash_bwd_block.

def _flash_carry_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref,
                        m_ref, l_ref, a_ref, mo_ref, lo_ref, ao_ref, *,
                        sm_scale: float, causal: bool, block_k: int):
    q = q_ref[0].astype(jnp.float32)             # (block_q, D)
    m = m_ref[0]
    l = l_ref[0]
    acc = a_ref[0].astype(jnp.float32)
    t_k = k_ref.shape[1]
    block_q = q.shape[0]
    qi = pl.program_id(1)
    q_pos = (qoff_ref[0] + qi * block_q
             + jax.lax.broadcasted_iota(jnp.int32,
                                        (block_q, block_k), 0))

    def body(i, carry):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        k_pos = (koff_ref[0] + i * block_k
                 + jax.lax.broadcasted_iota(jnp.int32,
                                            (block_q, block_k), 1))
        return _online_softmax_step(q, kb, vb, m, l, acc,
                                    sm_scale=sm_scale, causal=causal,
                                    q_pos=q_pos, k_pos=k_pos)

    m, l, acc = jax.lax.fori_loop(0, t_k // block_k, body, (m, l, acc))
    mo_ref[0] = m
    lo_ref[0] = l
    ao_ref[0] = acc.astype(ao_ref.dtype)


def flash_block_update(q: jax.Array, k_blk: jax.Array,
                       v_blk: jax.Array, m: jax.Array, l: jax.Array,
                       acc: jax.Array, q_off, k_off, *, causal: bool,
                       block_q: int = BLOCK_Q, block_k: int = BLOCK_K,
                       interpret: bool = False):
    """One ring-attention accumulate step, fused.

    q (BH, Tq, D) stays fixed; (k_blk, v_blk) (BH, Tk, D) is the block
    rotating past; (m, l, acc) is the online-softmax carry, updated and
    returned.  q_off/k_off are the blocks' global time offsets (traced
    int32 scalars — ring step index math), used for causal masking.
    Same algebra as parallel/sp.py accumulate()."""
    bh, t_q, d = q.shape
    t_k = k_blk.shape[1]
    if t_q % block_q or t_k % block_k:
        # a truncated grid would return partly-uninitialized carries
        raise ValueError(
            f"flash_block_update needs T divisible by the blocks: "
            f"t_q={t_q} % {block_q}, t_k={t_k} % {block_k}")
    sm_scale = 1.0 / math.sqrt(d)
    kern = functools.partial(_flash_carry_kernel, sm_scale=sm_scale,
                             causal=causal, block_k=block_k)
    qspec, kvspec, vec, _ = _flash_specs(block_q, d, t_k)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, t_q // block_q),
        in_specs=[qspec, kvspec, kvspec, vec, vec, qspec],
        out_specs=(vec, vec, qspec),
    )
    offs = (jnp.asarray([q_off], jnp.int32),
            jnp.asarray([k_off], jnp.int32))
    mo, lo, ao = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct((bh, t_q, 1), jnp.float32),
                   jax.ShapeDtypeStruct((bh, t_q, 1), jnp.float32),
                   jax.ShapeDtypeStruct((bh, t_q, d), acc.dtype)),
        interpret=interpret,
    )(*offs, q, k_blk, v_blk, m[:, :, None], l[:, :, None], acc)
    return mo[:, :, 0], lo[:, :, 0], ao
