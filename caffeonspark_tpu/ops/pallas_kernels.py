"""Pallas TPU kernels for ops XLA doesn't fuse optimally.

LRN ACROSS_CHANNELS (CaffeNet norm1/norm2 hot path): XLA lowers the
reduce_window over channels to a separate pass over HBM; the Pallas
kernel keeps each (C, spatial-tile) block resident in VMEM and computes
square → 5-wide channel-window sum (static shifted adds on the VPU) →
pow → divide in one fused pass, one HBM read + one write per element.

`lrn_across_channels(x, ...)` pads the flattened spatial dim to the
128-lane grid, runs the kernel per (batch, tile), and is used by
`ops.layers._lrn` when running on TPU (fallback: the XLA reduce_window
path — numerically identical, see tests/test_pallas.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE = 512  # spatial lanes per block (4 × 128)


def _window_sum(v: jax.Array, pad: int) -> jax.Array:
    """Σ over the symmetric channel window via static shifted adds (VPU)."""
    acc = v
    for off in range(1, pad + 1):
        down = jnp.concatenate(
            [jnp.zeros((off, v.shape[1]), v.dtype), v[:-off]], axis=0)
        up = jnp.concatenate(
            [v[off:], jnp.zeros((off, v.shape[1]), v.dtype)], axis=0)
        acc = acc + down + up
    return acc


def _lrn_kernel(x_ref, o_ref, s_ref, *, local_size: int, alpha: float,
                beta: float, k: float):
    x = x_ref[0]                     # (C, TILE) resident in VMEM
    pad = local_size // 2
    scale = k + (alpha / local_size) * _window_sum(x * x, pad)
    s_ref[0] = scale
    o_ref[0] = x * jnp.exp(-beta * jnp.log(scale))


def _lrn_kernel_fwd_only(x_ref, o_ref, *, local_size: int, alpha: float,
                         beta: float, k: float):
    """Inference variant: no scale residual output (XLA cannot DCE an
    unused output of an opaque kernel, so a separate kernel saves an
    activation-sized HBM write on the eval path)."""
    x = x_ref[0]
    pad = local_size // 2
    scale = k + (alpha / local_size) * _window_sum(x * x, pad)
    o_ref[0] = x * jnp.exp(-beta * jnp.log(scale))


def _lrn_bwd_kernel(x_ref, s_ref, dy_ref, dx_ref, *, local_size: int,
                    alpha: float, beta: float):
    """dx = dy·s^{-β} − (2αβ/n)·x·Σ_{i∈W} dy_i·x_i·s_i^{-β-1}."""
    x = x_ref[0]
    s = s_ref[0]
    dy = dy_ref[0]
    pad = local_size // 2
    s_nb = jnp.exp(-beta * jnp.log(s))        # s^{-β}
    u = dy * x * s_nb / s                      # dy·x·s^{-β-1}
    dx_ref[0] = dy * s_nb - (2.0 * alpha * beta / local_size) * x \
        * _window_sum(u, pad)


def _pad_flat(x):
    n, c, h, w = x.shape
    hw = h * w
    padded = (hw + TILE - 1) // TILE * TILE
    xf = x.reshape(n, c, hw)
    if padded != hw:
        xf = jnp.pad(xf, ((0, 0), (0, 0), (0, padded - hw)))
    return xf, hw, padded


def _block_spec(c):
    return pl.BlockSpec((1, c, TILE), lambda i, j: (i, 0, j),
                        memory_space=pltpu.VMEM)


def _lrn_fwd_call(x, local_size, alpha, beta, k, interpret):
    n, c, h, w = x.shape
    xf, hw, padded = _pad_flat(x)
    kern = functools.partial(_lrn_kernel, local_size=local_size,
                             alpha=alpha, beta=beta, k=k)
    out, scale = pl.pallas_call(
        kern,
        out_shape=(jax.ShapeDtypeStruct((n, c, padded), x.dtype),
                   jax.ShapeDtypeStruct((n, c, padded), x.dtype)),
        grid=(n, padded // TILE),
        in_specs=[_block_spec(c)],
        out_specs=(_block_spec(c), _block_spec(c)),
        interpret=interpret,
    )(xf)
    return (out[:, :, :hw].reshape(n, c, h, w),
            scale[:, :, :hw].reshape(n, c, h, w))


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def lrn_across_channels(x: jax.Array, local_size: int = 5,
                        alpha: float = 1e-4, beta: float = 0.75,
                        k: float = 1.0,
                        interpret: bool = False) -> jax.Array:
    """(N, C, H, W) float32 → LRN, Caffe semantics (alpha/local_size).
    Differentiable: a second fused kernel computes the exact VJP using
    saved denominators, so training runs on the Pallas path too; the
    undifferentiated primal uses a residual-free kernel."""
    n, c, h, w = x.shape
    xf, hw, padded = _pad_flat(x)
    kern = functools.partial(_lrn_kernel_fwd_only, local_size=local_size,
                             alpha=alpha, beta=beta, k=k)
    out = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((n, c, padded), x.dtype),
        grid=(n, padded // TILE),
        in_specs=[_block_spec(c)],
        out_specs=_block_spec(c),
        interpret=interpret,
    )(xf)
    return out[:, :, :hw].reshape(n, c, h, w)


def _lrn_vjp_fwd(x, local_size, alpha, beta, k, interpret):
    out, scale = _lrn_fwd_call(x, local_size, alpha, beta, k, interpret)
    return out, (x, scale)


def _lrn_vjp_bwd(local_size, alpha, beta, k, interpret, res, dy):
    x, scale = res
    n, c, h, w = x.shape
    xf, hw, padded = _pad_flat(x)
    sf, _, _ = _pad_flat(scale)
    # padded scale regions are 0 → guard: set them to 1 (u is 0 there)
    if padded != hw:
        mask = jnp.arange(padded) < hw
        sf = jnp.where(mask[None, None, :], sf, 1.0)
    dyf, _, _ = _pad_flat(dy)
    kern = functools.partial(_lrn_bwd_kernel, local_size=local_size,
                             alpha=alpha, beta=beta)
    dx = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((x.shape[0], c, padded), x.dtype),
        grid=(x.shape[0], padded // TILE),
        in_specs=[_block_spec(c), _block_spec(c), _block_spec(c)],
        out_specs=_block_spec(c),
        interpret=interpret,
    )(xf, sf, dyf)
    return (dx[:, :, :hw].reshape(n, c, h, w),)


lrn_across_channels.defvjp(_lrn_vjp_fwd, _lrn_vjp_bwd)


def pallas_enabled() -> bool:
    """Pallas kernels activate on real TPU backends only (CPU tests use
    interpret=True explicitly)."""
    import os
    if os.environ.get("COS_DISABLE_PALLAS"):
        return False
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False
