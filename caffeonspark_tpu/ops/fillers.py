"""Weight fillers with Caffe semantics (caffe-public filler.hpp behaviors,
referenced by every `weight_filler`/`bias_filler` in data/*.prototxt).

Supported types: constant, uniform, gaussian, xavier, msra, positive_unitball,
bilinear.  `xavier`/`msra` honor `variance_norm` (FAN_IN default).
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from ..proto.caffe import FillerParameter, VarianceNorm


def _fans(shape: Sequence[int]) -> Tuple[float, float]:
    """Caffe: fan_in = count/num, fan_out = count/channels for 4D blobs;
    for 2D (IP) weight (N, K): fan_in = K, fan_out = N."""
    if len(shape) == 0:
        return 1.0, 1.0
    count = math.prod(shape)
    fan_in = count / shape[0]
    fan_out = count / shape[1] if len(shape) > 1 else float(shape[0])
    return fan_in, fan_out


def _n_for(filler: FillerParameter, shape) -> float:
    fan_in, fan_out = _fans(shape)
    vn = filler.variance_norm
    if vn == VarianceNorm.FAN_OUT:
        return fan_out
    if vn == VarianceNorm.AVERAGE:
        return (fan_in + fan_out) / 2.0
    return fan_in


def fill(key: jax.Array, filler: FillerParameter, shape: Sequence[int],
         dtype=jnp.float32) -> jax.Array:
    t = filler.type or "constant"
    shape = tuple(int(s) for s in shape)
    if t == "constant":
        return jnp.full(shape, filler.value, dtype)
    if t == "uniform":
        return jax.random.uniform(key, shape, dtype, filler.min, filler.max)
    if t == "gaussian":
        return (filler.mean
                + filler.std * jax.random.normal(key, shape)).astype(dtype)
    if t == "xavier":
        scale = math.sqrt(3.0 / _n_for(filler, shape))
        return jax.random.uniform(key, shape, dtype, -scale, scale)
    if t == "msra":
        std = math.sqrt(2.0 / _n_for(filler, shape))
        return (std * jax.random.normal(key, shape)).astype(dtype)
    if t == "positive_unitball":
        x = jax.random.uniform(key, shape, dtype)
        flat = x.reshape(shape[0], -1)
        flat = flat / jnp.sum(flat, axis=1, keepdims=True)
        return flat.reshape(shape)
    if t == "bilinear":
        # upsampling kernel for Deconvolution (filler.hpp BilinearFiller)
        assert len(shape) == 4 and shape[2] == shape[3]
        k = shape[2]
        f = math.ceil(k / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        og = jnp.ogrid[:k, :k]
        w = (1 - jnp.abs(og[0] / f - c)) * (1 - jnp.abs(og[1] / f - c))
        return jnp.broadcast_to(w, shape).astype(dtype)
    raise ValueError(f"unknown filler type {t!r}")
