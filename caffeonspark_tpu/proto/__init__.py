"""Protobuf schema + runtime (text format / binary wire) for Caffe messages.

Equivalent of the reference's protobuf-java + caffe.proto usage
(`jcaffe/Utils.java:11-27`); see `descriptor.py` and `caffe.py`.
"""

from . import caffe
from .caffe import (BlobProto, BlobProtoVector, BlobShape, CoSDataParameter,
                    Datum, FillerParameter, LayerParameter, NetParameter,
                    NetState, NetStateRule, ParamSpec, Phase, SolverParameter,
                    SolverState, TopBlob, TopBlobType,
                    TransformationParameter)
from .descriptor import Enum, Field, Message


def parse_solver_prototxt(text: str) -> SolverParameter:
    """Text prototxt → SolverParameter (Utils.GetSolverParam analog)."""
    return SolverParameter.from_text(text)


def parse_net_prototxt(text: str) -> NetParameter:
    """Text prototxt → NetParameter (Utils.GetNetParam analog)."""
    return NetParameter.from_text(text)


def read_solver(path: str) -> SolverParameter:
    with open(path, "r") as f:
        return parse_solver_prototxt(f.read())


def read_net(path: str) -> NetParameter:
    with open(path, "r") as f:
        return parse_net_prototxt(f.read())
