"""Caffe message schema (reconstructed, plus CaffeOnSpark fork extensions).

The reference's schema lives in its absent `caffe-public` submodule
(`caffe.proto`); field numbers here follow upstream BVLC Caffe so that
binary `.caffemodel` / `.binaryproto` / `.solverstate` files and LMDB
`Datum` records interoperate.  CoS fork extensions (`source_class`,
`cos_data_param`, `MemoryDataParameter.{source,dataframe_format,
dataframe_column_select,image_encoded,share_in_parallel}`) have no public
numbers — they are visible only at call sites (SURVEY.md §2.9, e.g.
`DataSource.scala:139`, `ImageDataFrame.scala:35-45`) — so they are
assigned numbers in unclaimed ranges; only their *text*-format names
matter for config compatibility.
"""

from __future__ import annotations

from .descriptor import (BOOL, BYTES, DOUBLE, ENUM, FLOAT, INT32, INT64,
                         MESSAGE, STRING, UINT32, Enum, Field, Message)

# ---------------------------------------------------------------------------
# enums
# ---------------------------------------------------------------------------

Phase = Enum("Phase", TRAIN=0, TEST=1)
PoolMethod = Enum("PoolMethod", MAX=0, AVE=1, STOCHASTIC=2)
NormRegion = Enum("NormRegion", ACROSS_CHANNELS=0, WITHIN_CHANNEL=1)
EltwiseOp = Enum("EltwiseOp", PROD=0, SUM=1, MAX=2)
SnapshotFormat = Enum("SnapshotFormat", HDF5=0, BINARYPROTO=1)
SolverMode = Enum("SolverMode", CPU=0, GPU=1, TPU=2)
SolverType = Enum("SolverType", SGD=0, NESTEROV=1, ADAGRAD=2, RMSPROP=3,
                  ADADELTA=4, ADAM=5)
VarianceNorm = Enum("VarianceNorm", FAN_IN=0, FAN_OUT=1, AVERAGE=2)
DBBackend = Enum("DBBackend", LEVELDB=0, LMDB=1)
NormalizationMode = Enum("NormalizationMode", FULL=0, VALID=1, BATCH_SIZE=2,
                         NONE=3)
# CoS DataFrame top types (DataFrameSource.scala Top class, SURVEY §2.3)
TopBlobType = Enum("TopBlobType", STRING=0, INT=1, FLOAT=2, INT_ARRAY=3,
                   FLOAT_ARRAY=4, RAW_IMAGE=5, ENCODED_IMAGE=6,
                   ENCODED_IMAGE_WITH_DIM=7)


# ---------------------------------------------------------------------------
# basic blobs / data records
# ---------------------------------------------------------------------------

class BlobShape(Message):
    FIELDS = [Field(1, "dim", INT64, repeated=True, packed=True)]


class BlobProto(Message):
    FIELDS = [
        Field(7, "shape", MESSAGE, message=BlobShape),
        Field(5, "data", FLOAT, repeated=True, packed=True),
        Field(6, "diff", FLOAT, repeated=True, packed=True),
        Field(8, "double_data", DOUBLE, repeated=True, packed=True),
        Field(9, "double_diff", DOUBLE, repeated=True, packed=True),
        Field(1, "num", INT32),
        Field(2, "channels", INT32),
        Field(3, "height", INT32),
        Field(4, "width", INT32),
    ]


class BlobProtoVector(Message):
    FIELDS = [Field(1, "blobs", MESSAGE, message=BlobProto, repeated=True)]


class Datum(Message):
    """One LMDB record (image bytes CHW u8 or float_data, + label)."""
    FIELDS = [
        Field(1, "channels", INT32),
        Field(2, "height", INT32),
        Field(3, "width", INT32),
        Field(4, "data", BYTES),
        Field(5, "label", INT32),
        Field(6, "float_data", FLOAT, repeated=True),
        Field(7, "encoded", BOOL, default=False),
    ]


class FillerParameter(Message):
    FIELDS = [
        Field(1, "type", STRING, default="constant"),
        Field(2, "value", FLOAT, default=0.0),
        Field(3, "min", FLOAT, default=0.0),
        Field(4, "max", FLOAT, default=1.0),
        Field(5, "mean", FLOAT, default=0.0),
        Field(6, "std", FLOAT, default=1.0),
        Field(7, "sparse", INT32, default=-1),
        Field(8, "variance_norm", ENUM, enum=VarianceNorm, default=0),
    ]


# ---------------------------------------------------------------------------
# net state / rules / param specs
# ---------------------------------------------------------------------------

class NetState(Message):
    FIELDS = [
        Field(1, "phase", ENUM, enum=Phase, default=Phase.TEST),
        Field(2, "level", INT32, default=0),
        Field(3, "stage", STRING, repeated=True),
    ]


class NetStateRule(Message):
    FIELDS = [
        Field(1, "phase", ENUM, enum=Phase),
        Field(2, "min_level", INT32),
        Field(3, "max_level", INT32),
        Field(4, "stage", STRING, repeated=True),
        Field(5, "not_stage", STRING, repeated=True),
    ]


class ParamSpec(Message):
    FIELDS = [
        Field(1, "name", STRING),
        Field(2, "share_mode", ENUM,
              enum=Enum("DimCheckMode", STRICT=0, PERMISSIVE=1)),
        Field(3, "lr_mult", FLOAT, default=1.0),
        Field(4, "decay_mult", FLOAT, default=1.0),
    ]


# ---------------------------------------------------------------------------
# layer-specific parameter messages
# ---------------------------------------------------------------------------

class TransformationParameter(Message):
    FIELDS = [
        Field(1, "scale", FLOAT, default=1.0),
        Field(2, "mirror", BOOL, default=False),
        Field(3, "crop_size", UINT32, default=0),
        Field(4, "mean_file", STRING),
        Field(5, "mean_value", FLOAT, repeated=True),
        Field(6, "force_color", BOOL, default=False),
        Field(7, "force_gray", BOOL, default=False),
    ]


class LossParameter(Message):
    FIELDS = [
        Field(1, "ignore_label", INT32, default=-1),
        Field(3, "normalization", ENUM, enum=NormalizationMode, default=1),
        Field(2, "normalize", BOOL),
    ]


class AccuracyParameter(Message):
    FIELDS = [
        Field(1, "top_k", UINT32, default=1),
        Field(2, "axis", INT32, default=1),
        Field(3, "ignore_label", INT32, default=-1),
    ]


class ArgMaxParameter(Message):
    FIELDS = [
        Field(1, "out_max_val", BOOL, default=False),
        Field(2, "top_k", UINT32, default=1),
        Field(3, "axis", INT32),
    ]


class ConcatParameter(Message):
    FIELDS = [
        Field(2, "axis", INT32, default=1),
        Field(1, "concat_dim", UINT32, default=1),
    ]


class ConvolutionParameter(Message):
    FIELDS = [
        Field(1, "num_output", UINT32),
        Field(2, "bias_term", BOOL, default=True),
        Field(3, "pad", UINT32, repeated=True),
        Field(4, "kernel_size", UINT32, repeated=True),
        Field(6, "stride", UINT32, repeated=True),
        Field(18, "dilation", UINT32, repeated=True),
        Field(9, "pad_h", UINT32, default=0),
        Field(10, "pad_w", UINT32, default=0),
        Field(11, "kernel_h", UINT32),
        Field(12, "kernel_w", UINT32),
        Field(13, "stride_h", UINT32),
        Field(14, "stride_w", UINT32),
        Field(5, "group", UINT32, default=1),
        Field(7, "weight_filler", MESSAGE, message=FillerParameter),
        Field(8, "bias_filler", MESSAGE, message=FillerParameter),
        Field(15, "engine", ENUM,
              enum=Enum("Engine", DEFAULT=0, CAFFE=1, CUDNN=2)),
        Field(16, "axis", INT32, default=1),
        Field(17, "force_nd_im2col", BOOL, default=False),
    ]


class CropParameter(Message):
    FIELDS = [
        Field(1, "axis", INT32, default=2),
        Field(2, "offset", UINT32, repeated=True),
    ]


class DataParameter(Message):
    FIELDS = [
        Field(1, "source", STRING),
        Field(4, "batch_size", UINT32),
        Field(7, "rand_skip", UINT32, default=0),
        Field(8, "backend", ENUM, enum=DBBackend, default=0),
        Field(2, "scale", FLOAT, default=1.0),
        Field(3, "mean_file", STRING),
        Field(5, "crop_size", UINT32, default=0),
        Field(6, "mirror", BOOL, default=False),
        Field(9, "force_encoded_color", BOOL, default=False),
        Field(10, "prefetch", UINT32, default=4),
    ]


class DropoutParameter(Message):
    FIELDS = [Field(1, "dropout_ratio", FLOAT, default=0.5)]


class DummyDataParameter(Message):
    FIELDS = [
        Field(1, "data_filler", MESSAGE, message=FillerParameter,
              repeated=True),
        Field(6, "shape", MESSAGE, message=BlobShape, repeated=True),
        Field(2, "num", UINT32, repeated=True),
        Field(3, "channels", UINT32, repeated=True),
        Field(4, "height", UINT32, repeated=True),
        Field(5, "width", UINT32, repeated=True),
    ]


class EltwiseParameter(Message):
    FIELDS = [
        Field(1, "operation", ENUM, enum=EltwiseOp, default=EltwiseOp.SUM),
        Field(2, "coeff", FLOAT, repeated=True),
        Field(3, "stable_prod_grad", BOOL, default=True),
    ]


class ELUParameter(Message):
    FIELDS = [Field(1, "alpha", FLOAT, default=1.0)]


class EmbedParameter(Message):
    FIELDS = [
        Field(1, "num_output", UINT32),
        Field(2, "input_dim", UINT32),
        Field(3, "bias_term", BOOL, default=True),
        Field(4, "weight_filler", MESSAGE, message=FillerParameter),
        Field(5, "bias_filler", MESSAGE, message=FillerParameter),
    ]


class ExpParameter(Message):
    FIELDS = [
        Field(1, "base", FLOAT, default=-1.0),
        Field(2, "scale", FLOAT, default=1.0),
        Field(3, "shift", FLOAT, default=0.0),
    ]


class FlattenParameter(Message):
    FIELDS = [
        Field(1, "axis", INT32, default=1),
        Field(2, "end_axis", INT32, default=-1),
    ]


class HDF5DataParameter(Message):
    FIELDS = [
        Field(1, "source", STRING),
        Field(2, "batch_size", UINT32),
        Field(3, "shuffle", BOOL, default=False),
    ]


class HDF5OutputParameter(Message):
    FIELDS = [Field(1, "file_name", STRING)]


class HingeLossParameter(Message):
    FIELDS = [Field(1, "norm", ENUM, enum=Enum("Norm", L1=1, L2=2),
                    default=1)]


class ImageDataParameter(Message):
    FIELDS = [
        Field(1, "source", STRING),
        Field(4, "batch_size", UINT32, default=1),
        Field(7, "rand_skip", UINT32, default=0),
        Field(8, "shuffle", BOOL, default=False),
        Field(9, "new_height", UINT32, default=0),
        Field(10, "new_width", UINT32, default=0),
        Field(11, "is_color", BOOL, default=True),
        Field(2, "scale", FLOAT, default=1.0),
        Field(3, "mean_file", STRING),
        Field(5, "crop_size", UINT32, default=0),
        Field(6, "mirror", BOOL, default=False),
        Field(12, "root_folder", STRING),
    ]


class InfogainLossParameter(Message):
    FIELDS = [Field(1, "source", STRING), Field(2, "axis", INT32, default=1)]


class InnerProductParameter(Message):
    FIELDS = [
        Field(1, "num_output", UINT32),
        Field(2, "bias_term", BOOL, default=True),
        Field(3, "weight_filler", MESSAGE, message=FillerParameter),
        Field(4, "bias_filler", MESSAGE, message=FillerParameter),
        Field(5, "axis", INT32, default=1),
        Field(6, "transpose", BOOL, default=False),
    ]


class InputParameter(Message):
    FIELDS = [Field(1, "shape", MESSAGE, message=BlobShape, repeated=True)]


class LogParameter(Message):
    FIELDS = [
        Field(1, "base", FLOAT, default=-1.0),
        Field(2, "scale", FLOAT, default=1.0),
        Field(3, "shift", FLOAT, default=0.0),
    ]


class LRNParameter(Message):
    FIELDS = [
        Field(1, "local_size", UINT32, default=5),
        Field(2, "alpha", FLOAT, default=1.0),
        Field(3, "beta", FLOAT, default=0.75),
        Field(4, "norm_region", ENUM, enum=NormRegion, default=0),
        Field(5, "k", FLOAT, default=1.0),
    ]


class MemoryDataParameter(Message):
    # fields 1-4 are upstream; 100+ are CoS fork extensions
    # (ImageDataSource.scala:49-60, ImageDataFrame.scala:35-45,
    #  CaffeNet.cpp:183-188)
    FIELDS = [
        Field(1, "batch_size", UINT32),
        Field(2, "channels", UINT32),
        Field(3, "height", UINT32),
        Field(4, "width", UINT32),
        Field(100, "source", STRING),
        Field(101, "dataframe_format", STRING, default="parquet"),
        Field(102, "dataframe_column_select", STRING, repeated=True),
        Field(103, "image_encoded", BOOL, default=True),
        Field(104, "share_in_parallel", BOOL, default=False),
    ]


class ContrastiveLossParameter(Message):
    FIELDS = [
        Field(1, "margin", FLOAT, default=1.0),
        # legacy: penalize (margin - d^2) instead of (margin - d)^2
        Field(2, "legacy_version", BOOL, default=False),
    ]


class MVNParameter(Message):
    FIELDS = [
        Field(1, "normalize_variance", BOOL, default=True),
        Field(2, "across_channels", BOOL, default=False),
        Field(3, "eps", FLOAT, default=1e-9),
    ]


class ParameterParameter(Message):
    FIELDS = [Field(1, "shape", MESSAGE, message=BlobShape)]


class PoolingParameter(Message):
    FIELDS = [
        Field(1, "pool", ENUM, enum=PoolMethod, default=PoolMethod.MAX),
        Field(4, "pad", UINT32, default=0),
        Field(9, "pad_h", UINT32, default=0),
        Field(10, "pad_w", UINT32, default=0),
        Field(2, "kernel_size", UINT32),
        Field(5, "kernel_h", UINT32),
        Field(6, "kernel_w", UINT32),
        Field(3, "stride", UINT32, default=1),
        Field(7, "stride_h", UINT32),
        Field(8, "stride_w", UINT32),
        Field(12, "global_pooling", BOOL, default=False),
        Field(13, "round_mode", ENUM,
              enum=Enum("RoundMode", CEIL=0, FLOOR=1), default=0),
    ]


class PowerParameter(Message):
    FIELDS = [
        Field(1, "power", FLOAT, default=1.0),
        Field(2, "scale", FLOAT, default=1.0),
        Field(3, "shift", FLOAT, default=0.0),
    ]


class SPPParameter(Message):
    FIELDS = [
        Field(1, "pyramid_height", UINT32),
        Field(2, "pool", ENUM, enum=PoolMethod, default=PoolMethod.MAX),
    ]


class PReLUParameter(Message):
    FIELDS = [
        Field(1, "filler", MESSAGE, message=FillerParameter),
        Field(2, "channel_shared", BOOL, default=False),
    ]


class PythonParameter(Message):
    FIELDS = [
        Field(1, "module", STRING),
        Field(2, "layer", STRING),
        Field(3, "param_str", STRING),
        Field(4, "share_in_parallel", BOOL, default=False),
    ]


class RecurrentParameter(Message):
    FIELDS = [
        Field(1, "num_output", UINT32, default=0),
        Field(2, "weight_filler", MESSAGE, message=FillerParameter),
        Field(3, "bias_filler", MESSAGE, message=FillerParameter),
        Field(4, "debug_info", BOOL, default=False),
        Field(5, "expose_hidden", BOOL, default=False),
    ]


class ReductionParameter(Message):
    FIELDS = [
        Field(1, "operation", ENUM,
              enum=Enum("ReductionOp", SUM=1, ASUM=2, SUMSQ=3, MEAN=4),
              default=1),
        Field(2, "axis", INT32, default=0),
        Field(3, "coeff", FLOAT, default=1.0),
    ]


class ReLUParameter(Message):
    FIELDS = [Field(1, "negative_slope", FLOAT, default=0.0)]


class ReshapeParameter(Message):
    FIELDS = [
        Field(1, "shape", MESSAGE, message=BlobShape),
        Field(2, "axis", INT32, default=0),
        Field(3, "num_axes", INT32, default=-1),
    ]


class ScaleParameter(Message):
    FIELDS = [
        Field(1, "axis", INT32, default=1),
        Field(2, "num_axes", INT32, default=1),
        Field(3, "filler", MESSAGE, message=FillerParameter),
        Field(4, "bias_term", BOOL, default=False),
        Field(5, "bias_filler", MESSAGE, message=FillerParameter),
    ]


class BiasParameter(Message):
    FIELDS = [
        Field(1, "axis", INT32, default=1),
        Field(2, "num_axes", INT32, default=1),
        Field(3, "filler", MESSAGE, message=FillerParameter),
    ]


class BatchNormParameter(Message):
    FIELDS = [
        Field(1, "use_global_stats", BOOL),
        Field(2, "moving_average_fraction", FLOAT, default=0.999),
        Field(3, "eps", FLOAT, default=1e-5),
    ]


class SigmoidParameter(Message):
    FIELDS = []


class SliceParameter(Message):
    FIELDS = [
        Field(3, "axis", INT32, default=1),
        Field(2, "slice_point", UINT32, repeated=True),
        Field(1, "slice_dim", UINT32, default=1),
    ]


class SoftmaxParameter(Message):
    FIELDS = [Field(2, "axis", INT32, default=1)]


class TanHParameter(Message):
    FIELDS = []


class ThresholdParameter(Message):
    FIELDS = [Field(1, "threshold", FLOAT, default=0.0)]


class TileParameter(Message):
    FIELDS = [Field(1, "axis", INT32, default=1), Field(2, "tiles", INT32)]


# ---------------------------------------------------------------------------
# CoS fork: CoSData layer parameters (SURVEY §2.9, lrcn_cos.prototxt)
# ---------------------------------------------------------------------------

class TopBlob(Message):
    """One typed top of a CoSData layer (DataFrameSource.scala Top class)."""
    FIELDS = [
        Field(1, "name", STRING),
        Field(2, "type", ENUM, enum=TopBlobType, default=TopBlobType.FLOAT),
        Field(3, "channels", UINT32, default=1),
        Field(4, "height", UINT32, default=1),
        Field(5, "width", UINT32, default=1),
        Field(6, "out_channels", UINT32, default=0),
        Field(7, "out_height", UINT32, default=0),
        Field(8, "out_width", UINT32, default=0),
        Field(9, "sample_num_axes", INT32, default=3),
        Field(10, "transpose", BOOL, default=False),
        Field(11, "transform_param", MESSAGE,
              message=TransformationParameter),
    ]


class CoSDataParameter(Message):
    FIELDS = [
        Field(1, "batch_size", UINT32, default=1),
        Field(2, "source", STRING),
        Field(3, "dataframe_format", STRING, default="parquet"),
        Field(4, "top", MESSAGE, message=TopBlob, repeated=True),
    ]


class MoEParameter(Message):
    """Extension (no reference equivalent): top-k routed
    mixture-of-experts FFN with fixed expert capacity; the expert
    dimension shards over the ep mesh axis.  A second top, when
    declared, emits the load-balancing auxiliary loss (weight it via
    the layer's second loss_weight)."""
    FIELDS = [
        Field(1, "num_experts", UINT32, default=4),
        Field(2, "hidden_dim", UINT32, default=256),
        Field(3, "weight_filler", MESSAGE, message=FillerParameter),
        Field(4, "top_k", UINT32, default=1),
        Field(5, "capacity_factor", FLOAT, default=1.25),
    ]


class AttentionParameter(Message):
    """Extension (no reference equivalent): multi-head self-attention for
    long-context models.  The layer computes fused O(T²) attention that
    GSPMD partitions over whatever mesh axes the activations carry; for
    explicit O(T/S)-memory ring execution over the sp axis use
    `parallel.sp.ring_attention` directly."""
    FIELDS = [
        Field(1, "num_heads", UINT32, default=1),
        Field(2, "head_dim", UINT32, default=64),
        Field(3, "causal", BOOL, default=False),
        Field(4, "weight_filler", MESSAGE, message=FillerParameter),
    ]


# ---------------------------------------------------------------------------
# LayerParameter / NetParameter / SolverParameter
# ---------------------------------------------------------------------------

class LayerParameter(Message):
    FIELDS = [
        Field(1, "name", STRING),
        Field(2, "type", STRING),
        Field(3, "bottom", STRING, repeated=True),
        Field(4, "top", STRING, repeated=True),
        Field(10, "phase", ENUM, enum=Phase),
        Field(5, "loss_weight", FLOAT, repeated=True),
        Field(6, "param", MESSAGE, message=ParamSpec, repeated=True),
        Field(7, "blobs", MESSAGE, message=BlobProto, repeated=True),
        Field(11, "propagate_down", BOOL, repeated=True),
        Field(8, "include", MESSAGE, message=NetStateRule, repeated=True),
        Field(9, "exclude", MESSAGE, message=NetStateRule, repeated=True),
        # CoS fork extensions (numbers fork-private; text names are the API)
        Field(147, "source_class", STRING),
        Field(148, "cos_data_param", MESSAGE, message=CoSDataParameter),
        Field(149, "attention_param", MESSAGE, message=AttentionParameter),
        Field(150, "moe_param", MESSAGE, message=MoEParameter),
        # layer-specific params (upstream numbers)
        Field(100, "transform_param", MESSAGE,
              message=TransformationParameter),
        Field(101, "loss_param", MESSAGE, message=LossParameter),
        Field(102, "accuracy_param", MESSAGE, message=AccuracyParameter),
        Field(103, "argmax_param", MESSAGE, message=ArgMaxParameter),
        Field(139, "batch_norm_param", MESSAGE, message=BatchNormParameter),
        Field(141, "bias_param", MESSAGE, message=BiasParameter),
        Field(104, "concat_param", MESSAGE, message=ConcatParameter),
        Field(105, "contrastive_loss_param", MESSAGE,
              message=ContrastiveLossParameter),
        Field(132, "spp_param", MESSAGE, message=SPPParameter),
        Field(106, "convolution_param", MESSAGE,
              message=ConvolutionParameter),
        Field(144, "crop_param", MESSAGE, message=CropParameter),
        Field(107, "data_param", MESSAGE, message=DataParameter),
        Field(108, "dropout_param", MESSAGE, message=DropoutParameter),
        Field(109, "dummy_data_param", MESSAGE, message=DummyDataParameter),
        Field(110, "eltwise_param", MESSAGE, message=EltwiseParameter),
        Field(140, "elu_param", MESSAGE, message=ELUParameter),
        Field(137, "embed_param", MESSAGE, message=EmbedParameter),
        Field(111, "exp_param", MESSAGE, message=ExpParameter),
        Field(135, "flatten_param", MESSAGE, message=FlattenParameter),
        Field(112, "hdf5_data_param", MESSAGE, message=HDF5DataParameter),
        Field(113, "hdf5_output_param", MESSAGE,
              message=HDF5OutputParameter),
        Field(114, "hinge_loss_param", MESSAGE, message=HingeLossParameter),
        Field(115, "image_data_param", MESSAGE, message=ImageDataParameter),
        Field(116, "infogain_loss_param", MESSAGE,
              message=InfogainLossParameter),
        Field(117, "inner_product_param", MESSAGE,
              message=InnerProductParameter),
        Field(143, "input_param", MESSAGE, message=InputParameter),
        Field(134, "log_param", MESSAGE, message=LogParameter),
        Field(118, "lrn_param", MESSAGE, message=LRNParameter),
        Field(119, "memory_data_param", MESSAGE,
              message=MemoryDataParameter),
        Field(120, "mvn_param", MESSAGE, message=MVNParameter),
        Field(145, "parameter_param", MESSAGE, message=ParameterParameter),
        Field(121, "pooling_param", MESSAGE, message=PoolingParameter),
        Field(122, "power_param", MESSAGE, message=PowerParameter),
        Field(131, "prelu_param", MESSAGE, message=PReLUParameter),
        Field(130, "python_param", MESSAGE, message=PythonParameter),
        Field(146, "recurrent_param", MESSAGE, message=RecurrentParameter),
        Field(136, "reduction_param", MESSAGE, message=ReductionParameter),
        Field(123, "relu_param", MESSAGE, message=ReLUParameter),
        Field(133, "reshape_param", MESSAGE, message=ReshapeParameter),
        Field(142, "scale_param", MESSAGE, message=ScaleParameter),
        Field(124, "sigmoid_param", MESSAGE, message=SigmoidParameter),
        Field(126, "slice_param", MESSAGE, message=SliceParameter),
        Field(125, "softmax_param", MESSAGE, message=SoftmaxParameter),
        Field(127, "tanh_param", MESSAGE, message=TanHParameter),
        Field(128, "threshold_param", MESSAGE, message=ThresholdParameter),
        Field(138, "tile_param", MESSAGE, message=TileParameter),
    ]


# ---------------------------------------------------------------------------
# V1 legacy layers (deprecated upstream format still used by many published
# .caffemodel files, e.g. the original bvlc_reference_caffenet.caffemodel)
# ---------------------------------------------------------------------------

# V1LayerParameter.LayerType enum value → modern string type
V1_LAYER_TYPES = {
    35: "AbsVal", 1: "Accuracy", 30: "ArgMax", 2: "BNLL", 3: "Concat",
    37: "ContrastiveLoss", 4: "Convolution", 5: "Data", 39: "Deconvolution",
    6: "Dropout", 32: "DummyData", 7: "EuclideanLoss", 25: "Eltwise",
    38: "Exp", 8: "Flatten", 9: "HDF5Data", 10: "HDF5Output", 28: "HingeLoss",
    11: "Im2col", 12: "ImageData", 13: "InfogainLoss", 14: "InnerProduct",
    15: "LRN", 29: "MemoryData", 16: "MultinomialLogisticLoss", 34: "MVN",
    17: "Pooling", 26: "Power", 18: "ReLU", 19: "Sigmoid",
    27: "SigmoidCrossEntropyLoss", 36: "Silence", 20: "Softmax",
    21: "SoftmaxWithLoss", 22: "Split", 33: "Slice", 23: "TanH",
    24: "WindowData", 31: "Threshold",
}


class V1LayerParameter(Message):
    """Just enough of the deprecated layer message to import weights:
    name/type/blobs (+ topology for completeness)."""
    FIELDS = [
        Field(2, "bottom", STRING, repeated=True),
        Field(3, "top", STRING, repeated=True),
        Field(4, "name", STRING),
        Field(5, "type", ENUM,
              enum=Enum("V1LayerType", NONE=0, **{f"T{k}": k
                                                  for k in V1_LAYER_TYPES})),
        Field(6, "blobs", MESSAGE, message=BlobProto, repeated=True),
        Field(7, "blobs_lr", FLOAT, repeated=True),
        Field(8, "weight_decay", FLOAT, repeated=True),
    ]

    def type_name(self) -> str:
        return V1_LAYER_TYPES.get(int(self.type), f"V1:{int(self.type)}")


class NetParameter(Message):
    FIELDS = [
        Field(1, "name", STRING),
        Field(3, "input", STRING, repeated=True),
        Field(8, "input_shape", MESSAGE, message=BlobShape, repeated=True),
        Field(4, "input_dim", INT32, repeated=True),
        Field(5, "force_backward", BOOL, default=False),
        Field(6, "state", MESSAGE, message=NetState),
        Field(7, "debug_info", BOOL, default=False),
        Field(100, "layer", MESSAGE, message=LayerParameter, repeated=True),
        Field(2, "layers", MESSAGE, message=V1LayerParameter,
              repeated=True),
    ]


class SolverParameter(Message):
    FIELDS = [
        Field(24, "net", STRING),
        Field(25, "net_param", MESSAGE, message=NetParameter),
        Field(1, "train_net", STRING),
        Field(2, "test_net", STRING, repeated=True),
        Field(21, "train_net_param", MESSAGE, message=NetParameter),
        Field(22, "test_net_param", MESSAGE, message=NetParameter,
              repeated=True),
        Field(26, "train_state", MESSAGE, message=NetState),
        Field(27, "test_state", MESSAGE, message=NetState, repeated=True),
        Field(3, "test_iter", INT32, repeated=True),
        Field(4, "test_interval", INT32, default=0),
        Field(19, "test_compute_loss", BOOL, default=False),
        Field(32, "test_initialization", BOOL, default=True),
        Field(5, "base_lr", FLOAT),
        Field(6, "display", INT32),
        Field(33, "average_loss", INT32, default=1),
        Field(7, "max_iter", INT32),
        Field(36, "iter_size", INT32, default=1),
        Field(8, "lr_policy", STRING),
        Field(9, "gamma", FLOAT),
        Field(10, "power", FLOAT),
        Field(11, "momentum", FLOAT),
        Field(12, "weight_decay", FLOAT),
        Field(29, "regularization_type", STRING, default="L2"),
        Field(13, "stepsize", INT32),
        Field(34, "stepvalue", INT32, repeated=True),
        Field(35, "clip_gradients", FLOAT, default=-1.0),
        Field(14, "snapshot", INT32, default=0),
        Field(15, "snapshot_prefix", STRING),
        Field(16, "snapshot_diff", BOOL, default=False),
        Field(37, "snapshot_format", ENUM, enum=SnapshotFormat,
              default=SnapshotFormat.BINARYPROTO),
        Field(17, "solver_mode", ENUM, enum=SolverMode,
              default=SolverMode.GPU),
        Field(18, "device_id", INT32, default=0),
        Field(20, "random_seed", INT64, default=-1),
        Field(40, "type", STRING, default="SGD"),
        Field(31, "delta", FLOAT, default=1e-8),
        Field(39, "momentum2", FLOAT, default=0.999),
        Field(38, "rms_decay", FLOAT, default=0.99),
        Field(23, "debug_info", BOOL, default=False),
        Field(28, "snapshot_after_train", BOOL, default=True),
        Field(30, "solver_type", ENUM, enum=SolverType,
              default=SolverType.SGD),
    ]


class SolverState(Message):
    """Serialized optimizer state (.solverstate): iter + momentum history."""
    FIELDS = [
        Field(1, "iter", INT32),
        Field(2, "learned_net", STRING),
        Field(3, "history", MESSAGE, message=BlobProto, repeated=True),
        Field(4, "current_step", INT32, default=0),
    ]
