"""Minimal, self-contained protobuf runtime.

Provides just what the framework needs — no protoc, no google.protobuf
dependency:

  * a ``Message`` base class driven by ``Field`` descriptors,
  * Caffe-compatible **text format** (prototxt) parse / serialize,
  * **binary wire format** encode / decode (varints, fixed32/64,
    length-delimited, packed repeated) for ``Datum`` records,
    ``.caffemodel`` / ``.binaryproto`` / ``.solverstate`` files.

The reference obtains these from protobuf-java + the caffe.proto schema of
its (absent) caffe-public submodule; see SURVEY.md §2.9.  Re-implementing the
runtime keeps the rebuild dependency-free and lets the schema live as plain
Python (`caffeonspark_tpu/proto/caffe.py`).

Reference parity notes:
  * text parsing mirrors `jcaffe/Utils.java:11-27` (Get{Solver,Net}Param)
  * binary decode mirrors `LmdbRDD.scala:136-151` (Datum parse)
Unknown fields are skipped on decode (forward compatibility with real
caffemodels produced by other Caffe forks).
"""

from __future__ import annotations

import io
import struct
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Field types
# ---------------------------------------------------------------------------

DOUBLE = "double"
FLOAT = "float"
INT32 = "int32"
INT64 = "int64"
UINT32 = "uint32"
UINT64 = "uint64"
SINT32 = "sint32"
SINT64 = "sint64"
BOOL = "bool"
ENUM = "enum"
STRING = "string"
BYTES = "bytes"
MESSAGE = "message"

_VARINT_TYPES = {INT32, INT64, UINT32, UINT64, SINT32, SINT64, BOOL, ENUM}
_SCALAR_DEFAULTS = {
    DOUBLE: 0.0,
    FLOAT: 0.0,
    INT32: 0,
    INT64: 0,
    UINT32: 0,
    UINT64: 0,
    SINT32: 0,
    SINT64: 0,
    BOOL: False,
    ENUM: 0,
    STRING: "",
    BYTES: b"",
}

# wire types
_WT_VARINT = 0
_WT_FIXED64 = 1
_WT_LEN = 2
_WT_FIXED32 = 5


class Enum:
    """A named enum: Enum('Phase', TRAIN=0, TEST=1)."""

    def __init__(self, name: str, **values: int):
        self.name = name
        self.by_name: Dict[str, int] = dict(values)
        self.by_value: Dict[int, str] = {}
        for k, v in values.items():
            # first name wins for aliased values
            self.by_value.setdefault(v, k)
        for k, v in values.items():
            setattr(self, k, v)

    def value(self, name_or_val) -> int:
        if isinstance(name_or_val, int):
            return name_or_val
        if name_or_val in self.by_name:
            return self.by_name[name_or_val]
        raise ValueError(f"{self.name}: unknown enum value {name_or_val!r}")

    def name_of(self, val: int) -> str:
        return self.by_value.get(val, str(val))


class Field:
    """Descriptor for one protobuf field."""

    __slots__ = ("num", "name", "ftype", "repeated", "default", "enum",
                 "message", "packed")

    def __init__(self, num: int, name: str, ftype: str, *, repeated=False,
                 default=None, enum: Optional[Enum] = None, message=None,
                 packed=False):
        self.num = num
        self.name = name
        self.ftype = ftype
        self.repeated = repeated
        self.enum = enum
        self.message = message  # Message subclass (or callable returning it)
        self.packed = packed
        if default is None and not repeated and ftype != MESSAGE:
            default = _SCALAR_DEFAULTS[ftype]
        self.default = default

    def msg_cls(self):
        m = self.message
        # allow lazy references for recursive schemas
        if isinstance(m, str):
            raise TypeError("string message refs must be resolved at class "
                            "definition time")
        return m


class _RepeatedList(list):
    """List that notifies its owning message on first mutation, so lazily
    created sub-messages attach to their parent only when actually written
    (protobuf presence semantics: reading never creates fields)."""

    __slots__ = ("_owner",)

    def __init__(self, owner, *args):
        super().__init__(*args)
        self._owner = owner

    def _touch(self):
        self._owner._mark_modified()

    def append(self, v):
        super().append(v)
        self._touch()

    def extend(self, it):
        super().extend(it)
        self._touch()

    def insert(self, i, v):
        super().insert(i, v)
        self._touch()

    def __setitem__(self, i, v):
        super().__setitem__(i, v)
        self._touch()

    def __iadd__(self, other):
        res = super().__iadd__(other)
        self._touch()
        return res


class _MessageMeta(type):
    def __new__(mcls, name, bases, ns):
        cls = super().__new__(mcls, name, bases, ns)
        fields: List[Field] = list(ns.get("FIELDS", ()))
        cls._fields_by_name = {f.name: f for f in fields}
        cls._fields_by_num = {f.num: f for f in fields}
        return cls


class Message(metaclass=_MessageMeta):
    """Base message. Subclasses define FIELDS = [Field(...), ...]."""

    FIELDS: List[Field] = []

    def __init__(self, **kwargs):
        object.__setattr__(self, "_values", {})
        object.__setattr__(self, "_attach_cb", None)
        for k, v in kwargs.items():
            setattr(self, k, v)

    # -- attribute protocol --------------------------------------------------
    #
    # Reading an unset field NEVER creates it (protobuf presence semantics):
    # scalars return the default; sub-messages / repeated fields return a
    # lazily-attached placeholder that only materializes in the parent when
    # first *written* (so `cfg.state.phase` leaves cfg unchanged, while
    # `cfg.state.phase = TRAIN` vivifies the whole chain).

    def _mark_modified(self):
        cb = self._attach_cb
        if cb is not None:
            parent, fname = cb
            parent._values[fname] = self
            object.__setattr__(self, "_attach_cb", None)
            parent._mark_modified()

    def __getattr__(self, name):
        fields = type(self)._fields_by_name
        if name in fields:
            f = fields[name]
            vals = self._values
            if name not in vals:
                if f.repeated:
                    vals[name] = _RepeatedList(self)
                elif f.ftype == MESSAGE:
                    sub = f.msg_cls()()
                    object.__setattr__(sub, "_attach_cb", (self, name))
                    return sub
                else:
                    return f.default
            return vals[name]
        raise AttributeError(f"{type(self).__name__} has no field {name!r}")

    def __setattr__(self, name, value):
        f = type(self)._fields_by_name.get(name)
        if f is None:
            raise AttributeError(f"{type(self).__name__} has no field {name!r}")
        if f.repeated and not isinstance(value, list):
            # numpy arrays are kept as-is for packed float/double fields
            # (materializing 60M PyFloats for a caffemodel is pathological)
            if not (f.packed and f.ftype in (FLOAT, DOUBLE)
                    and type(value).__name__ == "ndarray"):
                value = list(value)
        if f.ftype == ENUM and not f.repeated and isinstance(value, str):
            value = f.enum.value(value)
        self._values[name] = value
        self._mark_modified()

    def has(self, name: str) -> bool:
        v = self._values.get(name)
        if v is None:
            return False
        f = type(self)._fields_by_name[name]
        if f.repeated:
            return len(v) > 0
        return True

    def clear(self, name: str) -> None:
        self._values.pop(name, None)

    def copy_from(self, other: "Message") -> "Message":
        assert type(self) is type(other)
        self._values.clear()
        self.merge_binary(other.to_binary())
        return self

    def clone(self):
        c = type(self)()
        c.copy_from(self)
        return c

    def __eq__(self, other):
        return (type(self) is type(other)
                and self.to_binary() == other.to_binary())

    def __repr__(self):
        body = self.to_text()
        if len(body) > 400:
            body = body[:400] + "…"
        return f"<{type(self).__name__}\n{body}>"

    # -- text format ---------------------------------------------------------

    def to_text(self, indent: int = 0) -> str:
        out: List[str] = []
        pad = "  " * indent
        for f in self.FIELDS:
            if not self.has(f.name):
                continue
            vals = self._values[f.name]
            if not f.repeated:
                vals = [vals]
            for v in vals:
                if f.ftype == MESSAGE:
                    out.append(f"{pad}{f.name} {{\n{v.to_text(indent + 1)}{pad}}}\n")
                elif f.ftype == ENUM:
                    out.append(f"{pad}{f.name}: {f.enum.name_of(v)}\n")
                elif f.ftype == STRING:
                    esc = (v.replace("\\", "\\\\").replace('"', '\\"')
                           .replace("\n", "\\n"))
                    out.append(f'{pad}{f.name}: "{esc}"\n')
                elif f.ftype == BYTES:
                    esc = "".join(
                        chr(b) if 0x20 <= b < 0x7F and b not in (0x22, 0x5C)
                        else f"\\{b:03o}" for b in v)
                    out.append(f'{pad}{f.name}: "{esc}"\n')
                elif f.ftype == BOOL:
                    out.append(f"{pad}{f.name}: {'true' if v else 'false'}\n")
                elif f.ftype in (FLOAT, DOUBLE):
                    # float() coercion: v may be a numpy scalar whose repr
                    # ('np.float32(x)') would not re-parse
                    out.append(f"{pad}{f.name}: {float(v)!r}\n")
                else:
                    out.append(f"{pad}{f.name}: {int(v)!r}\n")
        return "".join(out)

    @classmethod
    def from_text(cls, text: str) -> "Message":
        msg = cls()
        tok = _Tokenizer(text)
        _parse_fields(msg, tok, top_level=True)
        return msg

    # -- binary wire format --------------------------------------------------

    def to_binary(self) -> bytes:
        out = io.BytesIO()
        for f in self.FIELDS:
            if not self.has(f.name):
                continue
            vals = self._values[f.name]
            if not f.repeated:
                vals = [vals]
            if f.packed and f.repeated and f.ftype != MESSAGE:
                if f.ftype in (FLOAT, DOUBLE):
                    # numpy fast path: 60M-param caffemodels would take
                    # minutes through per-float struct.pack
                    import numpy as _np
                    b = _np.asarray(
                        vals, "<f4" if f.ftype == FLOAT else "<f8"
                    ).tobytes()
                else:
                    payload = io.BytesIO()
                    for v in vals:
                        _write_scalar(payload, f, v)
                    b = payload.getvalue()
                _write_key(out, f.num, _WT_LEN)
                _write_varint(out, len(b))
                out.write(b)
                continue
            for v in vals:
                if f.ftype == MESSAGE:
                    b = v.to_binary()
                    _write_key(out, f.num, _WT_LEN)
                    _write_varint(out, len(b))
                    out.write(b)
                elif f.ftype == STRING:
                    b = v.encode("utf-8")
                    _write_key(out, f.num, _WT_LEN)
                    _write_varint(out, len(b))
                    out.write(b)
                elif f.ftype == BYTES:
                    _write_key(out, f.num, _WT_LEN)
                    _write_varint(out, len(v))
                    out.write(v)
                elif f.ftype == FLOAT:
                    _write_key(out, f.num, _WT_FIXED32)
                    out.write(struct.pack("<f", v))
                elif f.ftype == DOUBLE:
                    _write_key(out, f.num, _WT_FIXED64)
                    out.write(struct.pack("<d", v))
                else:
                    _write_key(out, f.num, _WT_VARINT)
                    _write_scalar(out, f, v)
        return out.getvalue()

    @classmethod
    def from_binary(cls, data: bytes) -> "Message":
        msg = cls()
        msg.merge_binary(data)
        return msg

    def merge_binary(self, data: bytes) -> "Message":
        # malformed wire data must surface as ValueError (the codec's
        # documented failure mode) — never a leaked struct.error from a
        # fixed32/fixed64 read off a truncated buffer, an IndexError
        # from a varint cut mid-byte, or an OverflowError from an
        # absurd corrupted length
        try:
            return self._merge_binary_impl(data)
        except (struct.error, IndexError, OverflowError) as e:
            raise ValueError(
                f"malformed protobuf wire data: "
                f"{type(e).__name__}: {e}") from e

    def _merge_binary_impl(self, data: bytes) -> "Message":
        view = memoryview(data)
        pos = 0
        n = len(view)
        fields = type(self)._fields_by_num
        while pos < n:
            key, pos = _read_varint(view, pos)
            fnum, wt = key >> 3, key & 7
            f = fields.get(fnum)
            if f is None:
                pos = _skip(view, pos, wt)
                continue
            if wt == _WT_LEN:
                ln, pos = _read_varint(view, pos)
                if pos + ln > n:
                    raise ValueError("truncated length-delimited field")
                chunk = view[pos:pos + ln]
                pos += ln
                if f.ftype == MESSAGE:
                    sub = f.msg_cls()()
                    sub.merge_binary(chunk)
                    self._append(f, sub)
                elif f.ftype == STRING:
                    self._append(f, bytes(chunk).decode("utf-8", "replace"))
                elif f.ftype == BYTES:
                    self._append(f, bytes(chunk))
                elif (f.ftype == FLOAT and ln % 4 == 0) \
                        or (f.ftype == DOUBLE and ln % 8 == 0):
                    # packed float/double: bulk numpy decode, stored as
                    # an ndarray (list-compatible for our consumers)
                    import numpy as _np
                    arr = _np.frombuffer(
                        chunk, "<f4" if f.ftype == FLOAT else "<f8"
                    ).copy()
                    prev = self._values.get(f.name)
                    if prev is None or len(prev) == 0:
                        self._values[f.name] = arr
                    else:
                        self._values[f.name] = _np.concatenate(
                            [_np.asarray(prev, arr.dtype), arr])
                else:
                    # packed repeated scalars
                    p = 0
                    m = len(chunk)
                    while p < m:
                        v, p = _read_scalar(chunk, p, f)
                        self._append(f, v)
            elif wt == _WT_VARINT:
                v, pos = _read_varint(view, pos)
                self._append(f, _coerce_varint(f, v))
            elif wt == _WT_FIXED32:
                v = struct.unpack_from("<f" if f.ftype == FLOAT else "<I",
                                       view, pos)[0]
                pos += 4
                self._append(f, v)
            elif wt == _WT_FIXED64:
                v = struct.unpack_from("<d" if f.ftype == DOUBLE else "<Q",
                                       view, pos)[0]
                pos += 8
                self._append(f, v)
            else:
                raise ValueError(f"bad wire type {wt}")
        return self

    def _append(self, f: Field, v: Any) -> None:
        if f.repeated:
            cur = self._values.get(f.name)
            if cur is None:
                self._values[f.name] = [v]
            elif isinstance(cur, list):
                cur.append(v)
            else:  # ndarray from a packed fast-path decode; spec allows
                   # packed and unpacked elements interleaved
                self._values[f.name] = list(cur) + [v]
        else:
            self._values[f.name] = v


# ---------------------------------------------------------------------------
# wire helpers
# ---------------------------------------------------------------------------

def _write_varint(out, v: int) -> None:
    if v < 0:
        v += 1 << 64
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.write(bytes((b | 0x80,)))
        else:
            out.write(bytes((b,)))
            return


def _write_key(out, fnum: int, wt: int) -> None:
    _write_varint(out, (fnum << 3) | wt)


def _write_scalar(out, f: Field, v) -> None:
    if f.ftype == FLOAT:
        out.write(struct.pack("<f", v))
    elif f.ftype == DOUBLE:
        out.write(struct.pack("<d", v))
    elif f.ftype in (SINT32, SINT64):
        _write_varint(out, (v << 1) ^ (v >> 63))
    elif f.ftype == BOOL:
        _write_varint(out, 1 if v else 0)
    else:
        _write_varint(out, int(v))


def _read_varint(buf, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def _read_scalar(buf, pos: int, f: Field) -> Tuple[Any, int]:
    if f.ftype == FLOAT:
        return struct.unpack_from("<f", buf, pos)[0], pos + 4
    if f.ftype == DOUBLE:
        return struct.unpack_from("<d", buf, pos)[0], pos + 8
    v, pos = _read_varint(buf, pos)
    return _coerce_varint(f, v), pos


def _coerce_varint(f: Field, v: int):
    if f.ftype == BOOL:
        return bool(v)
    if f.ftype in (SINT32, SINT64):
        return (v >> 1) ^ -(v & 1)
    if f.ftype == INT32:
        # negative int32 arrives as a 64-bit sign-extended varint
        v &= (1 << 32) - 1
        return v - (1 << 32) if v >= 1 << 31 else v
    if f.ftype == INT64:
        v &= (1 << 64) - 1
        return v - (1 << 64) if v >= 1 << 63 else v
    if f.ftype == FLOAT:  # float stored packed comes through _read_scalar
        return v
    return v


def _skip(view, pos: int, wt: int) -> int:
    if wt == _WT_VARINT:
        _, pos = _read_varint(view, pos)
        return pos
    if wt == _WT_FIXED64:
        return pos + 8
    if wt == _WT_LEN:
        ln, pos = _read_varint(view, pos)
        if pos + ln > len(view):
            raise ValueError("truncated length-delimited field")
        return pos + ln
    if wt == _WT_FIXED32:
        return pos + 4
    raise ValueError(f"cannot skip wire type {wt}")


# ---------------------------------------------------------------------------
# text-format tokenizer / parser
# ---------------------------------------------------------------------------

class _Tokenizer:
    def __init__(self, text: str):
        self.text = text
        self.pos = 0
        self.n = len(text)
        self.line = 1

    def _skip_ws(self):
        t, n = self.text, self.n
        while self.pos < n:
            c = t[self.pos]
            if c == "#":
                while self.pos < n and t[self.pos] != "\n":
                    self.pos += 1
            elif c in " \t\r\n,":
                if c == "\n":
                    self.line += 1
                self.pos += 1
            else:
                return

    def peek(self) -> Optional[str]:
        self._skip_ws()
        if self.pos >= self.n:
            return None
        return self.text[self.pos]

    def next_token(self) -> str:
        self._skip_ws()
        if self.pos >= self.n:
            raise ValueError("unexpected end of prototxt")
        t = self.text
        c = t[self.pos]
        self.was_quoted = False
        if c in "{}:<>[];":
            self.pos += 1
            return c
        if c in "\"'":
            self.was_quoted = True
            return self._string(c)
        start = self.pos
        while (self.pos < self.n
               and t[self.pos] not in " \t\r\n{}:<>[]\"';,#"):
            self.pos += 1
        if start == self.pos:
            raise ValueError(f"bad token at line {self.line}: {c!r}")
        return t[start:self.pos]

    def _string(self, quote: str) -> str:
        # consumes a quoted string (with C escapes); adjacent strings concat
        out = []
        t = self.text
        self.pos += 1
        while True:
            if self.pos >= self.n:
                raise ValueError(f"unterminated string at line {self.line}")
            c = t[self.pos]
            if c == quote:
                self.pos += 1
                break
            if c == "\\":
                self.pos += 1
                if self.pos >= self.n:
                    raise ValueError(
                        f"unterminated string at line {self.line}")
                e = t[self.pos]
                if e in "01234567":
                    octs = e
                    while (len(octs) < 3 and self.pos + 1 < self.n
                           and t[self.pos + 1] in "01234567"):
                        self.pos += 1
                        octs += t[self.pos]
                    out.append(chr(int(octs, 8)))
                elif e == "x":
                    hx = ""
                    while (len(hx) < 2 and self.pos + 1 < self.n
                           and t[self.pos + 1] in "0123456789abcdefABCDEF"):
                        self.pos += 1
                        hx += t[self.pos]
                    if not hx:
                        raise ValueError(
                            f"bad \\x escape at line {self.line}")
                    out.append(chr(int(hx, 16)))
                else:
                    out.append({"n": "\n", "t": "\t", "r": "\r",
                                "\\": "\\", "'": "'", '"': '"',
                                "0": "\0"}.get(e, e))
                self.pos += 1
            else:
                out.append(c)
                self.pos += 1
        # implicit concatenation of adjacent string literals
        nxt = self.peek()
        if nxt in ("\"", "'"):
            out.append(self._string(nxt))
        return "".join(out)


_TRUE = {"true", "True", "1", "t"}
_FALSE = {"false", "False", "0", "f"}


def _parse_scalar(f: Field, tok_val: str):
    if f.ftype in (FLOAT, DOUBLE):
        return float(tok_val)
    if f.ftype == BOOL:
        if tok_val in _TRUE:
            return True
        if tok_val in _FALSE:
            return False
        raise ValueError(f"bad bool {tok_val!r} for field {f.name}")
    if f.ftype == ENUM:
        if tok_val.lstrip("-").isdigit():
            return int(tok_val)
        return f.enum.value(tok_val)
    if f.ftype == STRING:
        return tok_val
    if f.ftype == BYTES:
        return tok_val.encode("latin-1")
    return _parse_int(tok_val)


def _parse_int(tok: str) -> int:
    # protobuf text format: 0x.. hex, leading-zero octal, else decimal
    s = tok.lstrip("+-")
    sign = -1 if tok.startswith("-") else 1
    if s[:2].lower() == "0x":
        return sign * int(s, 16)
    if len(s) > 1 and s[0] == "0":
        return sign * int(s, 8)
    return sign * int(s, 10)


def _parse_fields(msg: Message, tok: _Tokenizer, *, top_level=False,
                  close: str = "}") -> None:
    fields = type(msg)._fields_by_name
    while True:
        c = tok.peek()
        if c is None:
            if top_level:
                return
            raise ValueError("unexpected EOF inside message block")
        if not top_level and c in (close, "}", ">"):
            tok.next_token()
            return
        name = tok.next_token()
        f = fields.get(name)
        if f is None:
            # protobuf TextFormat (and hence Caffe's ReadProtoFromText*)
            # fails on unknown fields — a typo'd config must not
            # silently misconfigure.  (Binary decode still skips
            # unknown tags for cross-fork caffemodel compat.)
            raise ValueError(
                f"line {tok.line}: unknown field {name!r} in "
                f"{type(msg).__name__}")
        c = tok.peek()
        if c == ":":
            tok.next_token()
            c = tok.peek()
        if c in ("{", "<"):
            opener = tok.next_token()
            closer = "}" if opener == "{" else ">"
            if f.ftype != MESSAGE:
                raise ValueError(f"field {name} is scalar but got a block")
            sub = f.msg_cls()()
            _parse_fields(sub, tok, close=closer)
            msg._append(f, sub)
        elif c == "[":
            # repeated scalar shorthand: f: [a, b, c]
            tok.next_token()
            while tok.peek() != "]":
                v = tok.next_token()
                _check_quoting(f, tok)
                msg._append(f, _parse_scalar(f, v))
            tok.next_token()
        else:
            v = tok.next_token()
            _check_quoting(f, tok)
            msg._append(f, _parse_scalar(f, v))


def _check_quoting(f: Field, tok: _Tokenizer) -> None:
    """TextFormat parity: string/bytes values must be quoted; numeric,
    bool, and enum values must not be."""
    quoted = getattr(tok, "was_quoted", False)
    if f.ftype in (STRING, BYTES):
        if not quoted:
            raise ValueError(
                f"line {tok.line}: string field {f.name!r} needs a "
                "quoted value")
    elif quoted:
        raise ValueError(
            f"line {tok.line}: field {f.name!r} ({f.ftype}) cannot take "
            "a quoted string value")


