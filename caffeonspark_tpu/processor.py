"""CaffeProcessor: per-executor training/inference engine.

Mirror of `caffe-grid/.../CaffeProcessor.scala` re-designed for a TPU
process: a per-process singleton (`instance()`, :20-30) that owns the
compiled Solver + mesh step, bounded feed queues with STOP_MARK /
backpressure semantics (:192-198, :205), transformer threads feeding a
device-prefetch pipe (:254-383 doTransform), a solver loop (:413-471
doTrain) with interleaved validation (queue 1, :388-411
updateValidationReport) and rank-0 snapshotting (:454-458), and a
feature-extraction path (:473-523 doFeatures).

The sync() barrier (:180-189) is retained for API parity; under SPMD it
only needs to order host-side epochs — collectives themselves are the
barrier.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time
from typing import (Any, Dict, Iterator, List, Optional,
                    Sequence, Tuple)

import numpy as np

_LOG = logging.getLogger(__name__)

from . import checkpoint
from .config import Config
from .data.queue_runner import (DROP_LIMIT_DEFAULT, DROPPED, FeedQueue,
                                TransformerPool, chunked_feed,
                                device_prefetch, stage_background,
                                stage_depth, steps_per_loop,
                                transform_threads, tune_decode_threads)
from .data.source import STOP_MARK, DataSource
from .metrics import PipelineMetrics
from .parallel import ParallelSolver, build_mesh, parse_mesh_spec
from .solver import Solver

# historical alias: the parser now lives with the mesh machinery
# (parallel.mesh.parse_mesh_spec — shared with the serving CLI)
_parse_mesh_spec = parse_mesh_spec


class ValidationReport:
    """Accumulates per-output means over batch × test_iter
    (updateValidationReport analog)."""

    def __init__(self, names: Sequence[str]):
        self.names = list(names)
        self.rounds: List[Dict[str, float]] = []
        self._acc: Dict[str, float] = {}
        self._n = 0

    def add_batch(self, outputs: Dict[str, Any]):
        for n in self.names:
            v = float(np.mean(np.asarray(outputs[n])))
            self._acc[n] = self._acc.get(n, 0.0) + v
        self._n += 1

    def finish_round(self):
        if self._n:
            self.rounds.append({n: self._acc[n] / self._n
                                for n in self.names})
        self._acc, self._n = {}, 0


class CaffeProcessor:
    _instance: Optional["CaffeProcessor"] = None

    # -- singleton protocol (CaffeProcessor.scala:20-30) -----------------
    @classmethod
    def instance(cls, conf: Optional[Config] = None, rank: int = 0
                 ) -> "CaffeProcessor":
        if conf is not None:
            # same Config object → same processor (so train() followed by
            # features()/test() keeps the in-memory trained params)
            if cls._instance is not None and cls._instance.conf is conf:
                return cls._instance
            if cls._instance is not None:
                cls._instance.stop()
            cls._instance = cls(conf, rank)
        assert cls._instance is not None, "processor not started"
        return cls._instance

    def __init__(self, conf: Config, rank: int = 0):
        from .data.source import get_source
        self.conf = conf
        self.rank = rank
        import jax
        devices = (jax.local_devices()[:conf.devices]
                   if conf.devices > 0
                   else None)  # -devices limits THIS host's devices
        if conf.mesh:
            mesh = build_mesh(devices=devices,
                              **_parse_mesh_spec(conf.mesh))
        else:
            mesh = build_mesh(devices=devices)
        # data sharding + rng seeding follow the mesh's DP coordinate
        # when processes form a jax.distributed cluster: tp/sp ranks
        # share replicated activations, so their augmentation/dropout
        # streams must match and every rank must feed the SAME records
        # (mini_cluster has the identical rule).  Outside a cluster
        # (Spark local engine, tests) the conf rank/clusterSize
        # semantics stand.
        if jax.process_count() > 1:
            from .parallel import dp_data_rank
            data_rank, data_ranks = dp_data_rank(mesh)
        else:
            data_rank, data_ranks = rank, max(1, conf.clusterSize)
        self.solver = Solver(conf.solverParameter, conf.netParam,
                             rank=data_rank)
        self.psolver = ParallelSolver(self.solver, mesh)
        self.queues = [FeedQueue(), FeedQueue()]   # 0 train, 1 validation
        self.results: List[Dict[str, Any]] = []
        self.validation: Optional[ValidationReport] = None
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._stopped = False
        # set by trainWithValidation: only then does anyone feed queue 1
        self.interleave_validation = False
        self.dropped_batches = 0      # driver reads this to re-sync feeds
        self.dropped_val_batches = 0  # informational (round shrinks)
        self._consecutive_drops = 0
        self._consecutive_val_drops = 0
        # pack runs on pool worker threads while validation packs on
        # the solver thread: all drop accounting shares one lock
        self._drop_lock = threading.Lock()
        self.metrics = PipelineMetrics()  # step-timeline (stop() dumps)
        self._flusher = None          # COS_METRICS_FLUSH_S (start())
        self._obs_server = None       # COS_METRICS_PORT (start())
        self._train_pool: Optional[TransformerPool] = None
        self._val_pool: Optional[TransformerPool] = None
        self._snapshotter = None      # lazy AsyncSnapshotter (-async_snapshot)
        self._val_shardings = None    # set when the val feed splits
        self.params = None
        self.opt_state = None

        seed = int(conf.solverParameter.random_seed) \
            if conf.solverParameter.random_seed >= 0 else 0
        self._source_kw = dict(rank=data_rank, num_ranks=data_ranks,
                               seed=seed, resize=conf.resize)
        tl = conf.train_data_layer()
        self.train_source: Optional[DataSource] = (
            get_source(tl, phase_train=True, **self._source_kw)
            if tl is not None and conf.isTraining else None)
        vl = conf.test_data_layer()
        self.val_source: Optional[DataSource] = (
            get_source(vl, phase_train=False, **self._source_kw)
            if vl is not None else None)

    # -- queue API (feedQueue backpressure, :192-198) --------------------
    def feed_queue(self, idx: int, sample) -> bool:
        return self.queues[idx].offer(sample)

    def mark_epoch_end(self, idx: int = 0):
        self.queues[idx].mark_epoch_end()

    def sync(self):
        """Cluster barrier analog — host-side ordering only."""
        return True

    # -- lifecycle -------------------------------------------------------
    def start(self):
        self._init_params()
        for q in self.queues:       # re-arm after a previous run stopped
            q.reset()
        self._train_pool = None     # _run_train builds fresh pools
        self._val_pool = None
        # observability: periodic summary flush to <output>/metrics.json
        # (COS_METRICS_FLUSH_S — a SIGKILLed run keeps telemetry) and
        # the live metrics port (COS_METRICS_PORT)
        if self._flusher is None and self.rank == 0:
            from .metrics import maybe_start_flusher
            self._flusher = maybe_start_flusher(
                self.metrics, getattr(self.conf, "outputPath", ""))
        if self._obs_server is None and self.rank == 0:
            from .obs.http import maybe_start_obs_server
            self._obs_server = maybe_start_obs_server(
                self.metrics.summary, role="trainer")
        self._thread = threading.Thread(target=self._run_train,
                                        daemon=True)
        self._thread.start()

    def _init_params(self):
        if self.params is not None:
            return
        params, st = self.psolver.init()
        conf = self.conf
        if conf.snapshotStateFile:
            params, st = checkpoint.restore(
                self.solver.train_net, params, st,
                conf.snapshotStateFile,
                weights_path=conf.snapshotModelFile or None)
            params = self.psolver.shard_params(params)
            st = self.psolver.shard_opt_state(st)
        elif conf.snapshotModelFile:
            params = checkpoint.copy_layers(
                self.solver.train_net, params, conf.snapshotModelFile)
            params = self.psolver.shard_params(params)
        self.params, self.opt_state = params, st

    def stop(self):
        self._stopped = True
        for q in self.queues:
            q.stop()
        if self._thread is not None:
            self._thread.join(timeout=600)
            self._thread = None
        snap_err = None
        if self._snapshotter is not None:   # pending write-behind lands
            try:
                self._snapshotter.wait(timeout=600)
            except BaseException as e:      # noqa: BLE001
                snap_err = e                # must not mask train error
        if self._obs_server is not None:
            self._obs_server.stop()
            self._obs_server = None
        if self._flusher is not None:       # final flush at stop
            self._flusher.stop()
            self._flusher = None
        self._dump_metrics()
        CaffeProcessor._instance = None
        if self._error is not None:
            raise self._error
        if snap_err is not None:
            raise snap_err

    def _dump_metrics(self):
        """Step-timeline dump at shutdown: one INFO line always, plus a
        JSON artifact when COS_PIPELINE_METRICS names a path."""
        m = self.metrics
        if not m.has_samples():
            return
        summary = m.summary()
        _LOG.info("pipeline metrics: %s",
                  json.dumps(summary, sort_keys=True))
        path = os.environ.get("COS_PIPELINE_METRICS")
        if path:
            try:
                m.dump(path)
            except OSError as e:
                _LOG.warning("could not write pipeline metrics to "
                             "%s: %s", path, e)

    def join(self):
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    # -- training loop (doTrain, :413-471) -------------------------------
    def _train_batches(self) -> Iterator[Dict[str, np.ndarray]]:
        assert self.train_source is not None
        src = self.train_source
        buf: List = []
        while not self._stopped:
            try:
                item = self.queues[0].take(timeout=1.0)
            except queue.Empty:
                continue
            if item is STOP_MARK:
                buf = []       # epoch boundary: drop ragged tail
                continue
            if item is None:
                return         # terminal sentinel
            buf.append(item)
            if len(buf) == src.batch_size:
                batch = self._pack_or_drop(src, buf)
                if batch is not None:
                    yield batch
                buf = []

    MAX_CONSECUTIVE_DROPS = DROP_LIMIT_DEFAULT

    def _note_pack_ok(self, *, val: bool = False):
        with self._drop_lock:
            if val:
                self._consecutive_val_drops = 0
            else:
                self._consecutive_drops = 0

    def _note_pack_drop(self, e: Exception, *, val: bool = False):
        """Thread-safe drop accounting shared by the transformer pool's
        workers and the inline validation pack — the reference's
        per-iteration failure tolerance (CaffeProcessor.scala:449-451).
        A run of consecutive failures means a systematic config error
        and aborts (raises) instead of spinning forever.  Train and
        validation keep SEPARATE consecutive counters: the pools pack
        concurrently, and a healthy train feed must not keep resetting
        the streak of a systematically failing validation source (or
        vice versa).  Drop totals are also separate: only TRAIN drops
        make the driver top up the train feed (a dropped validation
        batch already advanced the round counter, so topping up train
        records for it would skew the cadence)."""
        with self._drop_lock:
            if val:
                self._consecutive_val_drops += 1
                consecutive = self._consecutive_val_drops
                self.dropped_val_batches += 1
            else:
                self._consecutive_drops += 1
                consecutive = self._consecutive_drops
                self.dropped_batches += 1
        self.metrics.incr("dropped_val_batches" if val
                          else "dropped_batches")
        _LOG.warning("dropping batch after record error: %s", e)
        if consecutive >= self.MAX_CONSECUTIVE_DROPS:
            raise RuntimeError(
                f"{consecutive} consecutive batch failures — "
                f"systematic data/config error; last: {e}") from e

    def _pack_or_drop(self, src, buf, *, val: bool = False):
        """Inline pack with the drop policy (validation rounds and the
        COS_TRANSFORM_THREADS=0 legacy train path)."""
        t0 = time.perf_counter()
        try:
            batch = src.next_batch(buf)
        except Exception as e:
            self._note_pack_drop(e, val=val)   # raises at the limit
            return None
        self.metrics.add("pack", time.perf_counter() - t0)
        self._note_pack_ok(val=val)
        return batch

    def _run_train(self):
        gen = None
        try:
            import jax
            solver, ps = self.solver, self.psolver
            # gradient-exchange plan (COS_GRAD_SYNC) into the
            # step-timeline artifact: every pipeline-metrics JSON
            # states the per-step wire bytes / buckets / wire dtype
            gs = getattr(solver, "grad_sync", None)
            if gs is not None:
                self.metrics.set_info("comm", gs.plan.comm_info())
            # autotune plan (COS_AUTOTUNE) into the artifact exactly
            # like info.comm/info.sync: {"active": false} when unset,
            # else the plan key + per-layer variants applied
            self.metrics.set_info(
                "autotune", solver.train_net.autotune_info())
            # unified chaos layer (tools/chaos.py): the driver path
            # honors the step-delay / die-once / slow-rank injectors
            # too, and publishes the resolved plan so every metrics
            # artifact states what was injected.  The sync-mode policy
            # rides along (the driver is one process — the relaxed
            # modes' cross-rank exchange lives in mini_cluster; here
            # lockstep IS the only shape, but the artifact says so).
            from .tools.chaos import make_injector
            inj = make_injector(self.rank)
            self.metrics.set_info("faults", inj.plan.describe())
            self.metrics.set_info(
                "sync", getattr(solver, "sync_policy").describe())
            step = ps.train_step()
            eval_step = (ps.eval_step()
                         if solver.test_net is not None else None)
            sp = solver.param
            test_interval = sp.test_interval
            test_iter = solver.test_iter
            snap = sp.snapshot or 0
            max_iter = sp.max_iter
            if eval_step is not None and solver.test_net is not None:
                self.validation = ValidationReport(
                    solver.test_net.output_blobs)
            it = int(jax.device_get(self.opt_state.iter))
            from .data.queue_runner import combine_batches
            tmajor = frozenset(
                n for n, _, kind in solver.train_net.input_specs
                if kind.endswith(":T"))
            dxf = (self.train_source.enable_device_transform(
                       solver.train_net.dtype)
                   if self.train_source is not None else None)
            # validation feed takes the same split (center crop on
            # uint8 host-side, mean/scale on device before eval_step);
            # the stage output must carry eval_step's input shardings
            self._val_shardings = None
            if self.val_source is not None and solver.test_net is not None:
                if self.val_source.enable_device_transform(
                        solver.test_net.dtype):
                    self._val_shardings = ps.input_shardings(
                        solver.test_net)
            # pipelined ingest (the tentpole): a threaded transformer
            # pool packs batches off the solver thread, and the device
            # stager (H2D + jitted device-transform dispatch) runs on
            # its own background thread — the solver thread only ever
            # waits on ready, staged batches.  COS_TRANSFORM_THREADS=0
            # keeps the legacy inline path (pack + stage on the solver
            # thread).
            nthreads = transform_threads()
            src = self.train_source
            if nthreads > 0 and src is not None:
                tune_decode_threads(src, nthreads)
                self._train_pool = TransformerPool(
                    self.queues[0], src.batch_size,
                    pack=src.pack_batch, draw_fn=src.make_draw_fn(),
                    num_threads=nthreads,
                    on_pack_ok=self._note_pack_ok,
                    on_pack_error=lambda e: self._note_pack_drop(e),
                    metrics=self.metrics,
                    should_stop=lambda: self._stopped).start()
                batches = iter(self._train_pool)
            else:
                batches = self._train_batches()
            if (nthreads > 0 and self.interleave_validation
                    and self.val_source is not None
                    and eval_step is not None):
                vsrc = self.val_source
                # one pack worker: validation packs ahead between
                # rounds and is off the latency-critical path — extra
                # threads would only pressure the train pool
                self._val_pool = TransformerPool(
                    self.queues[1], vsrc.batch_size,
                    pack=vsrc.pack_batch, draw_fn=vsrc.make_draw_fn(),
                    num_threads=1,
                    on_pack_ok=lambda: self._note_pack_ok(val=True),
                    on_pack_error=lambda e: self._note_pack_drop(
                        e, val=True),
                    metrics=self.metrics,
                    should_stop=lambda: self._stopped).start()
            # fused multi-step loop (COS_STEPS_PER_LOOP=K>1): K packed
            # batches stack into one (K, batch…) block and one XLA
            # dispatch runs K solver iterations (LR schedule, iter
            # counter and rng advance on-device).  chunk_schedule keeps
            # every chunk inside the boundaries this loop ACTS on —
            # the interleaved-validation interval and the snapshot
            # cadence (single-step remainders otherwise), so both keep
            # their exact iterations; an interval with no action here
            # (display — this loop never logs it; test_interval with
            # validation off) must NOT throttle fusion.  K=1 is the
            # legacy per-step path.
            k_loop = steps_per_loop()
            fused_step = (ps.train_step_many(k_loop)
                          if k_loop > 1 else None)
            will_validate = (self.interleave_validation and test_interval
                             and eval_step is not None and test_iter)
            feed = chunked_feed(
                combine_batches(batches, max(1, sp.iter_size), tmajor),
                start_iter=it, max_iter=max_iter, k=k_loop,
                boundaries=(test_interval if will_validate else 0,
                            snap),
                metrics=self.metrics)
            gen = device_prefetch(
                feed, depth=stage_depth(),
                sharding=ps.input_shardings(),
                chunked=True,
                chunk_sharding=(ps.chunk_input_shardings()
                                if k_loop > 1 else None),
                device_transforms=dxf,
                background=nthreads > 0 and stage_background(),
                metrics=self.metrics)
            params, st = self.params, self.opt_state
            m = self.metrics
            while True:
                inj.step_delay()
                inj.maybe_die(it)
                t_wait = time.perf_counter()
                try:
                    n, batch = next(gen)
                except StopIteration:
                    break
                m.add("queue_wait", time.perf_counter() - t_wait)
                m.gauge("feed_depth", len(self.queues[0]))
                t_step = time.perf_counter()
                if n == 1:
                    params, st, out = step(params, st, batch,
                                           solver.step_rng(it))
                    it += 1
                    m.add("step", time.perf_counter() - t_step)
                    m.mark_step()
                else:
                    params, st, out = fused_step(params, st, batch)
                    it += n
                    m.add_chunk(n, time.perf_counter() - t_step)
                inj.slow_sleep(time.perf_counter() - t_step)
                # interleaved validation: rank-0 records, all ranks step
                if self.interleave_validation and test_interval \
                        and it % test_interval == 0 \
                        and eval_step is not None and test_iter:
                    self._run_validation(eval_step, params, test_iter)
                if snap and it % snap == 0:
                    # the multi-host tp/ep param gather is a COLLECTIVE
                    # — every rank runs it at this lockstep boundary
                    # (no-op otherwise); non-rank0 then participates
                    # only to write its ZeRO state-shard sidecar
                    export_p = checkpoint.gather_params_if_sharded(
                        params)
                    if self.rank == 0 \
                            or checkpoint.state_is_sharded(st):
                        self.params, self.opt_state = params, st
                        self._snapshot(export_params=export_p)
                if it >= max_iter:
                    break
            self.params, self.opt_state = params, st
            if sp.snapshot_after_train:
                export_p = checkpoint.gather_params_if_sharded(params)
                if self.rank == 0 \
                        or checkpoint.state_is_sharded(st):
                    self._snapshot(final=True, export_params=export_p)
        except BaseException as e:     # surfaced on stop()/join()
            from .obs.recorder import maybe_dump, record
            record("trainer", "fatal",
                   error=f"{type(e).__name__}: {e}")
            maybe_dump("fatal_exception")
            self._error = e
        finally:
            # tear the pipeline down in dependency order: close the
            # stager generator first (its finally unblocks a stager
            # thread stuck on a full handoff queue), then flag the
            # pools down, then unblock feeders spinning in offer()
            # (backpressure release)
            if gen is not None:
                try:
                    gen.close()
                except Exception:       # noqa: BLE001
                    pass
            for pool in (self._train_pool, self._val_pool):
                if pool is not None:
                    pool.stop(join_timeout=2.0)
            for q in self.queues:
                q.stop()

    VALIDATION_STALL_TIMEOUT = 30.0

    def _run_validation(self, eval_step, params, test_iter: int):
        assert self.val_source is not None
        src = self.val_source
        if self._val_pool is not None:
            self._run_validation_pooled(eval_step, params, test_iter)
            return
        buf: List = []
        done = 0
        while done < test_iter and not self._stopped:
            try:
                item = self.queues[1].take(
                    timeout=self.VALIDATION_STALL_TIMEOUT)
            except queue.Empty:
                if self._stopped or self.queues[1].stopped:
                    break          # ordinary shutdown mid-validation
                # a stalled validation feeder must not silently shrink
                # the round (round-1 VERDICT weak spot 5): fail loudly —
                # the solver thread surfaces this on stop()/join()
                raise RuntimeError(
                    f"validation feed stalled: {done}/{test_iter} "
                    "batches after 30s — feeder dead or test source "
                    "exhausted (check test_iter x batch_size vs "
                    "dataset size)")
            if item is STOP_MARK or item is None:
                continue
            buf.append(item)
            if len(buf) == src.batch_size:
                batch = self._pack_or_drop(src, buf, val=True)
                if batch is not None:
                    batch = src.apply_device_stage(
                        batch, self._val_shardings)
                    out = eval_step(params, batch)
                    self.validation.add_batch(out)
                buf = []
                done += 1
        self.validation.finish_round()

    def _run_validation_pooled(self, eval_step, params,
                               test_iter: int):
        """Validation round over the queue-1 transformer pool: batches
        arrive packed (and in feed order), the solver thread only runs
        eval steps.  A DROPPED slot still advances the round counter —
        the old inline loop's semantics (the feeder already spent the
        records)."""
        src = self.val_source
        done = 0
        while done < test_iter and not self._stopped:
            try:
                batch = self._val_pool.take(
                    timeout=self.VALIDATION_STALL_TIMEOUT,
                    skip_dropped=False)
            except queue.Empty:
                if self._stopped or self.queues[1].stopped:
                    break          # ordinary shutdown mid-validation
                raise RuntimeError(
                    f"validation feed stalled: {done}/{test_iter} "
                    "batches after "
                    f"{self.VALIDATION_STALL_TIMEOUT:.0f}s — feeder "
                    "dead or test source exhausted (check test_iter x "
                    "batch_size vs dataset size)")
            if batch is None:
                break              # pool terminal (stop/exhausted)
            if batch is not DROPPED:
                batch = src.apply_device_stage(
                    batch, self._val_shardings)
                out = eval_step(params, batch)
                self.validation.add_batch(out)
            done += 1
        self.validation.finish_round()

    def _snapshot(self, final: bool = False, export_params=None):
        conf = self.conf
        from .utils import fsutils
        prefix = fsutils.join(conf.outputPath or ".",
                              conf.solverParameter.snapshot_prefix
                              or "model")
        fmt = conf.solverParameter.snapshot_format
        write_main = self.rank == 0
        params = (export_params if export_params is not None
                  else self.params)
        if getattr(conf, "asyncSnapshot", False):
            if self._snapshotter is None:
                self._snapshotter = checkpoint.AsyncSnapshotter()
            self._snapshotter.submit(
                self.solver.train_net, params, self.opt_state,
                prefix, fmt=fmt, solver_type=self.solver.solver_type,
                write_main=write_main)
            if final:
                self._snapshotter.wait()
        else:
            checkpoint.snapshot(
                self.solver.train_net, params, self.opt_state,
                prefix, fmt=fmt, solver_type=self.solver.solver_type,
                write_main=write_main)
        if final and conf.modelPath and self.rank == 0:
            checkpoint.save_caffemodel(conf.modelPath,
                                       self.solver.train_net,
                                       params)

    # -- feature extraction (doFeatures, :473-523) ------------------------
    def extract_features(self, source: DataSource,
                         blob_names: Sequence[str]
                         ) -> List[Dict[str, Any]]:
        return self.extract_rows(source.records(), blob_names,
                                 source=source)

    def default_feature_blobs(self) -> List[str]:
        net = self.solver.test_net or self.solver.train_net
        names = list(net.output_blobs)
        label = getattr(self.conf, "label", "")
        if label and label not in names:
            names.append(label)     # -label column rides along
        return names

    def feature_source(self) -> Optional[DataSource]:
        """Record decoder for feature extraction, ALWAYS test-phase:
        the val source when the net has a TEST data layer, else one
        built in phase_train=False from whatever data layer exists —
        never train_source, whose transformer applies random
        crop/mirror and would make predictions nondeterministic."""
        src = self.val_source or getattr(self, "_feature_src", None)
        if src is None:
            lp = (self.conf.test_data_layer()
                  or self.conf.train_data_layer())
            if lp is not None:
                from .data.source import get_source
                src = get_source(lp, phase_train=False,
                                 **self._source_kw)
                self._feature_src = src
        return src

    def _feature_fwd(self, blob_names: Tuple[str, ...]):
        """Jitted predict(blobNames) closure, cached per blob set — the
        daemon's chunked EXTRACT requests must not retrace per chunk.
        The builder lives in serving/forward.py (shared with the online
        serving subsystem, which needs it without a training run).
        With an explicit -mesh the extract forward runs under the SAME
        MeshLayout the training step uses (mesh-parallel forward: tp/ep
        params stay sharded, batch over dp); the implicit all-dp
        default keeps the single-program path so extract output stays
        byte-identical to the pre-mesh behavior."""
        from .serving.forward import BlobForward
        net = self.solver.test_net or self.solver.train_net
        layout = (self.psolver.layout
                  if (getattr(self.conf, "mesh", "")
                      and self.psolver.mesh.devices.size > 1)
                  else None)
        fwd = getattr(self, "_blob_forward", None)
        if fwd is None or fwd.net is not net or fwd.layout is not layout:
            fwd = self._blob_forward = BlobForward(net, layout=layout)
        return fwd(blob_names)

    def extract_rows(self, records, blob_names: Sequence[str],
                     source: Optional[DataSource] = None
                     ) -> List[Dict[str, Any]]:
        """features()/predict core over an arbitrary record stream —
        the Spark path hands partition records in over the feed daemon
        (OP_EXTRACT) while the local path streams source.records()."""
        self._init_params()
        source = source or self.feature_source()
        assert source is not None, "no data layer to decode records with"
        fwd = self._feature_fwd(tuple(blob_names))
        feat_shardings = None
        if getattr(source, "_device_transform", False) \
                and self.psolver is not None:
            feat_shardings = self.psolver.input_shardings(
                self.solver.test_net or self.solver.train_net)
        rows: List[Dict[str, Any]] = []
        buf: List = []
        ids: List[str] = []

        from .serving.forward import fetch_rows

        def flush(real: int):
            """Run one batch and emit `real` rows (row extraction
            shared with serving via fetch_rows — one device_get per
            blob, not per row)."""
            nonlocal buf, ids
            bs = len(buf)
            # a split-enabled source (train-then-features on the same
            # processor) emits uint8+aux: finish the transform here,
            # placed on the mesh so mesh-sharded params and the input
            # agree on devices
            out = fwd(self.params,
                      source.apply_device_stage(source.next_batch(buf),
                                                feat_shardings))
            rows.extend(fetch_rows(out, blob_names, ids, real, bs))
            buf, ids = [], []

        for rec in records:
            buf.append(rec)
            ids.append(str(rec[0]) if isinstance(rec, tuple)
                       else str(rec.get("id", len(ids))))
            if len(buf) == source.batch_size:
                flush(real=len(buf))
        if buf:
            # ragged tail: pad to full batch (static shapes), trim rows
            real = len(buf)
            pad = source.batch_size - real
            buf += [buf[-1]] * pad
            ids += [ids[-1]] * pad
            flush(real=real)
        return rows
