"""ctypes binding + build for the native data-pipeline library.

The reference's native image path (jcaffe Mat → cv::imdecode,
FloatDataTransformer → caffe::DataTransformer, SURVEY §2.4) lives here
as `libcos_native.so` (libjpeg decode + threaded NCHW transform).  The
library builds on demand with g++ (Makefile equivalent: `make -C
caffeonspark_tpu/native`); when the toolchain or libjpeg is missing,
callers fall back to the cv2/numpy path in `data.transformer` /
`data.source` — same semantics.  Measured (tools/simulator.py): on a
single core the cv2 fallback is competitive (its SIMD decode beats
plain libjpeg); the native path's win is its thread pool on multi-core
executor hosts and independence from cv2.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libcos_native.so")
_SRC = os.path.join(_DIR, "cos_native.cpp")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def build(force: bool = False) -> bool:
    """Compile the shared library; returns True on success.  A shipped
    .so without the source (pruned deployment) is accepted as-is."""
    global _build_failed
    if os.path.exists(_SO) and not force:
        try:
            if (not os.path.exists(_SRC)
                    or os.path.getmtime(_SO) >= os.path.getmtime(_SRC)):
                return True
        except OSError:
            return True       # can't stat: trust the shipped .so
    if not os.path.exists(_SRC):
        return os.path.exists(_SO)
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           _SRC, "-o", _SO, "-ljpeg"]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=120)
        if r.returncode != 0:
            _build_failed = True
            return False
        return True
    except (OSError, subprocess.TimeoutExpired):
        _build_failed = True
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed); None when unavailable."""
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    with _lock:
        if _lib is not None:
            return _lib
        # build() is a no-op when the .so is current; a source edit
        # (newer mtime) triggers a rebuild so new symbols exist
        if not build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
            _bind(lib)
        except OSError:
            _build_failed = True
            return None
        except AttributeError:
            # stale .so lacking newer symbols (mtime-preserving copy):
            # one forced rebuild if the source is around, else give up
            # and let callers fall back to the cv2 path
            if not (os.path.exists(_SRC) and build(force=True)):
                _build_failed = True
                return None
            try:
                lib = ctypes.CDLL(_SO)
                _bind(lib)
            except (OSError, AttributeError):
                _build_failed = True
                return None
        _lib = lib
        return _lib


def _bind(lib) -> None:
    lib.cos_decode_batch.restype = ctypes.c_int
    lib.cos_decode_batch.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_long),
        ctypes.POINTER(ctypes.c_long), ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_float), ctypes.c_int]
    lib.cos_decode_batch_u8.restype = ctypes.c_int
    lib.cos_decode_batch_u8.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_long),
        ctypes.POINTER(ctypes.c_long), ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_ubyte), ctypes.c_int]
    lib.cos_transform_batch.restype = None
    lib.cos_transform_batch.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_ubyte),
        ctypes.POINTER(ctypes.c_float), ctypes.c_int,
        ctypes.c_float, ctypes.POINTER(ctypes.c_float), ctypes.c_int]
    lib.cos_crop_mirror_u8.restype = None
    lib.cos_crop_mirror_u8.argtypes = [
        ctypes.POINTER(ctypes.c_ubyte), ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_ubyte),
        ctypes.POINTER(ctypes.c_ubyte), ctypes.c_int]
    lib.cos_native_version.restype = ctypes.c_int


def available() -> bool:
    """COS_NATIVE=0 forces the cv2/numpy fallback — on few-core hosts
    cv2's SIMD decode beats libjpeg (see module docstring), and an
    ingest pool supplies its own inter-batch parallelism."""
    if os.environ.get("COS_NATIVE", "").lower() in ("0", "false", "no"):
        return False
    return get_lib() is not None


def decode_batch(images: Sequence[bytes], *, channels: int, out_h: int,
                 out_w: int, num_threads: int = 0,
                 out_dtype=np.float32) -> np.ndarray:
    """JPEG bytes → (N, C, out_h, out_w) BGR planes.

    out_dtype float32 (default) or uint8 — the uint8 path decodes
    straight into byte planes for the device-transform split
    (COS_DEVICE_TRANSFORM): no float buffer, no host cast pass, and
    its truncating store equals `float_output.astype(uint8)` exactly."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = len(images)
    blob = b"".join(images)
    offsets = np.zeros(n, np.int64)
    sizes = np.asarray([len(b) for b in images], np.int64)
    np.cumsum(sizes[:-1], out=offsets[1:]) if n > 1 else None
    if np.dtype(out_dtype) == np.uint8:
        out = np.empty((n, channels, out_h, out_w), np.uint8)
        fn, ptr = lib.cos_decode_batch_u8, ctypes.c_ubyte
    else:
        out = np.empty((n, channels, out_h, out_w), np.float32)
        fn, ptr = lib.cos_decode_batch, ctypes.c_float
    ok = fn(
        blob, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        n, channels, out_h, out_w,
        out.ctypes.data_as(ctypes.POINTER(ptr)), num_threads)
    if ok != n:
        raise ValueError(f"{n - ok}/{n} images failed to decode")
    return out


def transform_batch(batch: np.ndarray, *, crop: int = 0,
                    h_off: Optional[np.ndarray] = None,
                    w_off: Optional[np.ndarray] = None,
                    mirror: Optional[np.ndarray] = None,
                    mean: Optional[np.ndarray] = None,
                    scale: float = 1.0,
                    num_threads: int = 0) -> np.ndarray:
    """Caffe transform on an (N, C, H, W) float32 batch (native)."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    batch = np.ascontiguousarray(batch, np.float32)
    n, c, h, w = batch.shape
    oh = crop or h
    ow = crop or w
    out = np.empty((n, c, oh, ow), np.float32)
    zeros = np.zeros(n, np.int32)
    h_off = np.ascontiguousarray(h_off if h_off is not None else zeros,
                                 np.int32)
    w_off = np.ascontiguousarray(w_off if w_off is not None else zeros,
                                 np.int32)
    mir = np.ascontiguousarray(
        mirror if mirror is not None else np.zeros(n, np.uint8), np.uint8)
    if mean is None:
        mean_ptr, mode = None, 0
    elif mean.ndim == 1:
        mean = np.ascontiguousarray(mean, np.float32)
        mean_ptr, mode = mean.ctypes.data_as(
            ctypes.POINTER(ctypes.c_float)), 1
    else:
        mean = np.ascontiguousarray(mean, np.float32)
        assert mean.shape == (c, oh, ow), (mean.shape, (c, oh, ow))
        mean_ptr, mode = mean.ctypes.data_as(
            ctypes.POINTER(ctypes.c_float)), 2
    lib.cos_transform_batch(
        batch.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n, c, h, w, crop,
        h_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        w_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        mir.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
        mean_ptr, mode, ctypes.c_float(scale),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), num_threads)
    return out


def crop_mirror_u8(batch: np.ndarray, h_off: np.ndarray,
                   w_off: np.ndarray, mirror: np.ndarray, *,
                   crop: int = 0, num_threads: int = 0) -> np.ndarray:
    """Threaded uint8 crop(+mirror) — the device-transform split's host
    half (Transformer.host_stage's hot loop).  The RNG draws stay with
    the caller; this only moves bytes."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    batch = np.ascontiguousarray(batch, np.uint8)
    n, c, h, w = batch.shape
    oh = crop if crop else h
    ow = crop if crop else w
    ho = np.ascontiguousarray(h_off, np.int32)
    wo = np.ascontiguousarray(w_off, np.int32)
    mi = np.ascontiguousarray(mirror, np.uint8)
    out = np.empty((n, c, oh, ow), np.uint8)
    lib.cos_crop_mirror_u8(
        batch.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
        n, c, h, w, crop,
        ho.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        wo.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        mi.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)), num_threads)
    return out
