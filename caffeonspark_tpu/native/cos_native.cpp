// Native data-pipeline kernels: JPEG decode + batched transform.
//
// TPU-native equivalent of the reference's native image path —
// cv::imdecode via jcaffe Mat (caffe-distri/src/main/cpp/jni/JniMat.cpp)
// and caffe::DataTransformer via FloatDataTransformer
// (jni/JniFloatDataTransformer.cpp) — feeding preallocated NCHW float
// buffers.  Exposed as a plain C ABI for ctypes (no pybind11 in this
// image).  Threading: one worker per hardware thread across the batch
// (the transformer-thread-pool analog of CaffeProcessor.scala:54-55).
//
// Layout notes: decode emits BGR channel order (OpenCV convention, which
// Caffe models expect) as planar CHW float32.  Resize is bilinear.

#include <cstddef>
#include <cstdio>

#include <jpeglib.h>

#include <algorithm>
#include <atomic>
#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct JpegErr {
  jpeg_error_mgr pub;
  jmp_buf jmp;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* err = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(err->jmp, 1);
}

// decode JPEG bytes to interleaved rows; returns false on corrupt input
bool decode_jpeg_raw(const unsigned char* data, long size, int channels,
                     std::vector<unsigned char>* pixels, int* h, int* w) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jmp)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(data),
               static_cast<unsigned long>(size));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = channels == 1 ? JCS_GRAYSCALE : JCS_RGB;
  jpeg_start_decompress(&cinfo);
  *h = cinfo.output_height;
  *w = cinfo.output_width;
  int comps = cinfo.output_components;
  pixels->resize(static_cast<size_t>(*h) * *w * comps);
  while (cinfo.output_scanline < cinfo.output_height) {
    unsigned char* row =
        pixels->data() + static_cast<size_t>(cinfo.output_scanline) * *w * comps;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// bilinear resize + HWC(RGB) → CHW(BGR).  Dst is float or uint8; the
// uint8 store TRUNCATES (matches numpy astype(uint8) on the float
// output, so the uint8-infeed path equals cast(float path) exactly).
template <typename T>
void resize_to_chw(const unsigned char* src, int sh, int sw, int channels,
                   int dh, int dw, T* dst) {
  const float ys = dh > 1 ? static_cast<float>(sh - 1) / (dh - 1) : 0.0f;
  const float xs = dw > 1 ? static_cast<float>(sw - 1) / (dw - 1) : 0.0f;
  for (int y = 0; y < dh; ++y) {
    float fy = y * ys;
    int y0 = static_cast<int>(fy);
    int y1 = std::min(y0 + 1, sh - 1);
    float wy = fy - y0;
    for (int x = 0; x < dw; ++x) {
      float fx = x * xs;
      int x0 = static_cast<int>(fx);
      int x1 = std::min(x0 + 1, sw - 1);
      float wx = fx - x0;
      for (int c = 0; c < channels; ++c) {
        const float p00 = src[(y0 * sw + x0) * channels + c];
        const float p01 = src[(y0 * sw + x1) * channels + c];
        const float p10 = src[(y1 * sw + x0) * channels + c];
        const float p11 = src[(y1 * sw + x1) * channels + c];
        float v = p00 * (1 - wy) * (1 - wx) + p01 * (1 - wy) * wx +
                  p10 * wy * (1 - wx) + p11 * wy * wx;
        // BGR plane order: plane (channels-1-c) receives RGB channel c
        int plane = channels == 3 ? 2 - c : c;
        dst[(static_cast<size_t>(plane) * dh + y) * dw + x] =
            static_cast<T>(v);
      }
    }
  }
}

template <typename T>
int decode_batch_impl(const unsigned char* blob, const long* offsets,
                      const long* sizes, int n, int channels, int out_h,
                      int out_w, T* out, int num_threads) {
  std::atomic<int> ok(0);
  std::atomic<int> next(0);
  int nthreads = num_threads > 0
                     ? num_threads
                     : static_cast<int>(std::thread::hardware_concurrency());
  nthreads = std::max(1, std::min(nthreads, n));
  auto worker = [&]() {
    std::vector<unsigned char> pixels;
    for (int i = next.fetch_add(1); i < n; i = next.fetch_add(1)) {
      T* dst = out + static_cast<size_t>(i) * channels * out_h * out_w;
      int h = 0, w = 0;
      if (decode_jpeg_raw(blob + offsets[i], sizes[i], channels, &pixels,
                          &h, &w)) {
        resize_to_chw(pixels.data(), h, w, channels, out_h, out_w, dst);
        ok.fetch_add(1);
      } else {
        std::memset(dst, 0, sizeof(T) * channels * out_h * out_w);
      }
    }
  };
  std::vector<std::thread> pool;
  for (int t = 0; t < nthreads; ++t) pool.emplace_back(worker);
  for (auto& t : pool) t.join();
  return ok.load();
}

}  // namespace

extern "C" {

// Decode a batch of JPEGs into a preallocated (n, channels, out_h, out_w)
// float32 buffer (BGR planes).  offsets[i]/sizes[i] locate image i inside
// `blob`.  Returns the number of successfully decoded images; failed
// slots are zero-filled.
int cos_decode_batch(const unsigned char* blob, const long* offsets,
                     const long* sizes, int n, int channels, int out_h,
                     int out_w, float* out, int num_threads) {
  return decode_batch_impl(blob, offsets, sizes, n, channels, out_h,
                           out_w, out, num_threads);
}

// uint8 output variant for the device-transform split
// (COS_DEVICE_TRANSFORM): the feed ships 1 byte/pixel, so decode
// straight into uint8 planes — no float buffer, no host cast pass.
int cos_decode_batch_u8(const unsigned char* blob, const long* offsets,
                        const long* sizes, int n, int channels,
                        int out_h, int out_w, unsigned char* out,
                        int num_threads) {
  return decode_batch_impl(blob, offsets, sizes, n, channels, out_h,
                           out_w, out, num_threads);
}

// Caffe transform_param semantics on an NCHW float batch:
//   out[i] = (crop(mirror(in[i])) - mean) * scale
// h_off/w_off: per-image crop origins; mirror_flags: per-image 0/1.
// mean_mode: 0 none, 1 per-channel values (mean[c]), 2 full CHW plane
// (mean has crop*crop*c elements, already cropped by caller).
void cos_transform_batch(const float* in, int n, int c, int h, int w,
                         int crop, const int* h_off, const int* w_off,
                         const unsigned char* mirror_flags,
                         const float* mean, int mean_mode, float scale,
                         float* out, int num_threads) {
  const int oh = crop > 0 ? crop : h;
  const int ow = crop > 0 ? crop : w;
  std::atomic<int> next(0);
  int nthreads = num_threads > 0
                     ? num_threads
                     : static_cast<int>(std::thread::hardware_concurrency());
  nthreads = std::max(1, std::min(nthreads, n));
  auto worker = [&]() {
    for (int i = next.fetch_add(1); i < n; i = next.fetch_add(1)) {
      const float* src = in + static_cast<size_t>(i) * c * h * w;
      float* dst = out + static_cast<size_t>(i) * c * oh * ow;
      const int hs = crop > 0 ? h_off[i] : 0;
      const int ws = crop > 0 ? w_off[i] : 0;
      const bool mir = mirror_flags && mirror_flags[i];
      for (int ch = 0; ch < c; ++ch) {
        for (int y = 0; y < oh; ++y) {
          const float* srow =
              src + (static_cast<size_t>(ch) * h + hs + y) * w + ws;
          float* drow = dst + (static_cast<size_t>(ch) * oh + y) * ow;
          for (int x = 0; x < ow; ++x) {
            float v = srow[mir ? (ow - 1 - x) : x];
            if (mean_mode == 1) {
              v -= mean[ch];
            } else if (mean_mode == 2) {
              v -= mean[(static_cast<size_t>(ch) * oh + y) * ow + x];
            }
            drow[x] = v * scale;
          }
        }
      }
    }
  };
  std::vector<std::thread> pool;
  for (int t = 0; t < nthreads; ++t) pool.emplace_back(worker);
  for (auto& t : pool) t.join();
}

// Raw u8 CHW records (LMDB Datum payloads) → float NCHW, batched.
void cos_u8_to_float_batch(const unsigned char* in, long total,
                           float* out) {
  for (long i = 0; i < total; ++i)
    out[i] = static_cast<float>(in[i]);
}

// The device-transform split's host half, threaded: per-image crop
// window copy (+ optional horizontal mirror) on uint8 NCHW planes.
// h_off/w_off/mirror_flags are per-image (the Caffe RNG draws stay in
// Python so trajectories match the numpy path exactly); crop == 0
// means no crop (oh=h, ow=w).
void cos_crop_mirror_u8(const unsigned char* in, int n, int c, int h,
                        int w, int crop, const int* h_off,
                        const int* w_off,
                        const unsigned char* mirror_flags,
                        unsigned char* out, int num_threads) {
  const int oh = crop > 0 ? crop : h;
  const int ow = crop > 0 ? crop : w;
  std::atomic<int> next(0);
  int nthreads = num_threads > 0
                     ? num_threads
                     : static_cast<int>(std::thread::hardware_concurrency());
  nthreads = std::max(1, std::min(nthreads, n));
  auto worker = [&]() {
    for (int i = next.fetch_add(1); i < n; i = next.fetch_add(1)) {
      const unsigned char* src =
          in + static_cast<size_t>(i) * c * h * w;
      unsigned char* dst =
          out + static_cast<size_t>(i) * c * oh * ow;
      // no-crop mode ignores the offsets (sibling cos_transform_batch
      // rule): a nonzero offset with oh==h would read out of bounds
      const int hs = crop > 0 ? h_off[i] : 0;
      const int ws = crop > 0 ? w_off[i] : 0;
      const bool mir = mirror_flags[i] != 0;
      for (int ch = 0; ch < c; ++ch) {
        const unsigned char* sp = src + static_cast<size_t>(ch) * h * w;
        unsigned char* dp = dst + static_cast<size_t>(ch) * oh * ow;
        for (int y = 0; y < oh; ++y) {
          const unsigned char* row = sp + (hs + y) * w + ws;
          unsigned char* orow = dp + y * ow;
          if (!mir) {
            std::memcpy(orow, row, ow);
          } else {
            for (int x = 0; x < ow; ++x) orow[x] = row[ow - 1 - x];
          }
        }
      }
    }
  };
  std::vector<std::thread> pool;
  for (int t = 0; t < nthreads; ++t) pool.emplace_back(worker);
  for (auto& t : pool) t.join();
}

int cos_native_version() { return 1; }

}  // extern "C"
