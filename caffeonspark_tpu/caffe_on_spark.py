"""CaffeOnSpark: the driver API facade + CLI.

Public surface parity with `caffe-grid/.../CaffeOnSpark.scala`:
  * `main` CLI dispatch (-train / -test / -features, :27-84)
  * `train(source)` (:164-231)
  * `trainWithValidation(sourceTrain, sourceValidation)` (:239-358) —
    interleaved validation with fixed-size rounds, results as a
    DataFrame of per-round output means
  * `test(source)` (:396-418) — per-blob mean vectors (VectorMean UDAF)
  * `features(source)` / `features2` (:427-506) — SampleID + blob
    columns DataFrame

Engine: runs on the local process group by default (each process = one
"executor" owning the mesh).  When pyspark is importable and a
SparkContext is passed, the same driver logic dispatches partitions to
executors via `spark_backend` (optional; this environment ships no
pyspark, so that path is import-gated)."""

from __future__ import annotations

import itertools
import json
import os
import signal
import sys
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .config import Config
from .data.source import DataSource, get_source
from .processor import CaffeProcessor
from .utils import fsutils


class DataFrame:
    """Minimal columnar result set (stand-in for Spark's DataFrame in
    local mode): list-of-dict rows + schema, json/parquet writers."""

    def __init__(self, rows: List[Dict[str, Any]],
                 columns: Optional[Sequence[str]] = None):
        self.rows = rows
        self.columns = (list(columns) if columns is not None
                        else (list(rows[0].keys()) if rows else []))

    def __len__(self):
        return len(self.rows)

    def select(self, *cols) -> "DataFrame":
        return DataFrame([{c: r[c] for c in cols} for r in self.rows],
                         cols)

    def collect(self) -> List[Dict[str, Any]]:
        return self.rows

    def to_arrow(self):
        import pyarrow as pa
        return pa.table({c: [r.get(c) for r in self.rows]
                         for c in self.columns})

    def write(self, path: str, fmt: str = "json") -> None:
        if fmt == "json":
            with fsutils.open_file(path, "w") as f:
                for r in self.rows:
                    f.write(json.dumps(r) + "\n")
        elif fmt == "parquet":
            import pyarrow.parquet as pq
            with fsutils.open_file(path, "wb") as f:
                pq.write_table(self.to_arrow(), f)
        else:
            raise ValueError(f"outputFormat {fmt!r}")


def vector_mean(df: DataFrame, column: str) -> List[float]:
    """Element-wise mean of a float-array column (VectorMean.scala
    UDAF analog, used by test())."""
    arrs = [np.asarray(r[column], np.float64) for r in df.rows]
    if not arrs:
        return []
    return [float(x) for x in np.mean(np.stack(arrs), axis=0)]


class CaffeOnSpark:
    """Driver facade.  `sc` is accepted for API parity; local engine
    when None or pyspark is unavailable."""

    def __init__(self, sc=None):
        self.sc = sc

    # ------------------------------------------------------------------
    def _engine(self, conf: Config):
        """SparkEngine when `sc` is a usable SparkContext, else None
        (local engine).  The reference has no such fork — Spark IS its
        runtime; here local mode is first-class (TPU pods don't need a
        JVM) and a real `sc` upgrades train/trainWithValidation/features
        to the barrier-stage executor choreography transparently."""
        from . import spark as spark_mod
        if self.sc is None or not hasattr(self.sc, "parallelize"):
            return None
        # no spark_available() gate here: a live SparkContext proves a
        # working JVM gateway however it was launched (spark-submit
        # with a bundled JRE has no `java` on PATH) — the which-java
        # heuristic belongs to the pre-construction path in
        # _cli_spark_context only (round-4 advisor)
        return spark_mod.SparkEngine(self.sc, conf, require=False)

    def _engine_run(self, engine, make_feed) -> dict:
        """The driver re-feed loop (:204-227): feed, poll, repeat until
        the executor solvers reach max_iter; then join + shutdown.
        `make_feed` builds the per-round feed closure INSIDE the
        try/finally so a failure materializing sources still tears the
        executors down (orphaned daemons would hijack the app_id's next
        run).  Raises unless training verifiably completed."""
        rep = None
        try:
            feed_rounds = make_feed()
            for _ in range(1000):
                feed_rounds()
                rep = engine.collect_report()
                if rep is not None and not rep["alive"]:
                    break
            rep = engine.wait_done()
        finally:
            engine.shutdown()
        if rep is not None and rep.get("error"):
            raise RuntimeError(
                f"executor solver failed: {rep['error']}")
        if rep is None or rep.get("alive"):
            raise RuntimeError(
                "training did not complete: executor solver still "
                "running (or unreachable) after the re-feed loop — "
                "check executor logs / max_iter vs records fed")
        return rep

    # ------------------------------------------------------------------
    def train(self, source: DataSource, conf: Optional[Config] = None
              ) -> None:
        """Synchronous training over the mesh (CaffeOnSpark.train).
        The re-feed loop of the reference (:204-227, feeding the RDD
        until max_iter) is the processor's looping source feed; with a
        real SparkContext the records stream through the barrier-stage
        executors instead."""
        conf = conf or source_conf(source)
        engine = self._engine(conf)
        if engine is not None:
            engine.setup()

            def make_feed():
                # executor-side reads: each feed round = one epoch of
                # every rank's own shard, opened inside the task (the
                # records never pass through the driver)
                epochs = itertools.count()
                return lambda: engine.feed_source(source, 0,
                                                  next(epochs))

            self._engine_run(engine, make_feed)
            return
        proc = CaffeProcessor.instance(conf, rank=conf.rank)
        proc.start()
        try:
            self._feed_until_done(proc, source)
        finally:
            proc.queues[0].offer(None)
            proc.join()

    def trainWithValidation(self, source_train: DataSource,
                            source_validation: DataSource,
                            conf: Optional[Config] = None) -> DataFrame:
        """Interleaved train+validation (:239-358): every executor feeds
        test_interval×batch training records then test_iter×batch
        validation records, in lockstep; rank 0 records metrics."""
        conf = conf or source_conf(source_train)
        sp = conf.solverParameter
        test_interval = sp.test_interval
        test_iter = sp.test_iter[0] if sp.test_iter else 0
        if not test_interval or not test_iter:
            raise ValueError("trainWithValidation needs test_interval "
                             "and test_iter in the solver prototxt")
        engine = self._engine(conf)
        if engine is not None:
            engine.setup(interleave_validation=True)

            def make_feed():
                # train records: executor-side shard reads per round.
                # validation: one ROUND per feed round, sized exactly
                # test_iter x batch (the fixed-size validation
                # partition, CaffeOnSpark.scala:266,279-282) — feeding
                # the whole validation set each round would outrun the
                # solver's per-interval drain and deadlock on queue-1
                # backpressure.  The bounded val slice is the one
                # driver-materialized piece, by design.
                epochs = itertools.count()
                need = test_iter * source_validation.batch_size
                val_round = list(itertools.islice(
                    _record_loop(source_validation), need))
                val_rdd = self.sc.parallelize(val_round, 1)

                def rounds():
                    engine.feed_source(source_train, 0, next(epochs))
                    engine.feed_partitions(val_rdd, 1)
                return rounds

            rep = self._engine_run(engine, make_feed)
            val = (rep or {}).get("validation") or {}
            return DataFrame(val.get("rounds", []),
                             val.get("names", []))
        proc = CaffeProcessor.instance(conf, rank=conf.rank)
        proc.interleave_validation = True
        proc.start()
        try:
            train_bs = source_train.batch_size
            val_bs = source_validation.batch_size
            persistent = bool(getattr(conf, "isPersistent", False))
            train_gen = _record_loop(source_train, persistent=persistent)
            val_gen = _record_loop(source_validation,
                                   persistent=persistent)
            max_iter = sp.max_iter
            fed = 0
            drops_seen = 0
            while fed < max_iter and proc._thread.is_alive():
                # top up for batches the processor dropped (bad records)
                # so its iteration count stays in lockstep with the plan
                extra = proc.dropped_batches - drops_seen
                drops_seen = proc.dropped_batches
                for _ in range((test_interval + extra) * train_bs):
                    if not proc.feed_queue(0, next(train_gen)):
                        break
                fed += test_interval
                for _ in range(test_iter * val_bs):
                    if not proc.feed_queue(1, next(val_gen)):
                        break
        finally:
            proc.queues[0].offer(None)
            proc.join()
        report = proc.validation
        rows = report.rounds if report else []
        return DataFrame(rows, report.names if report else [])

    # ------------------------------------------------------------------
    def test(self, source: DataSource,
             conf: Optional[Config] = None) -> Dict[str, List[float]]:
        """Forward over the test set; per-output mean vectors
        (:396-418)."""
        df = self.features2(source, conf)
        names = [c for c in df.columns if c != "SampleID"]
        return {n: vector_mean(df, n) for n in names}

    def features(self, source: DataSource,
                 conf: Optional[Config] = None) -> DataFrame:
        """Feature extraction → DataFrame(SampleID, blobs...)
        (:427-438)."""
        return self.features2(source, conf)

    def features2(self, source: DataSource,
                  conf: Optional[Config] = None) -> DataFrame:
        conf = conf or source_conf(source)
        blob_names = [b.strip() for b in conf.features.split(",")
                      if b.strip()] if conf.features else None
        if blob_names and conf.label and conf.label not in blob_names:
            blob_names.append(conf.label)
        engine = self._engine(conf)
        if engine is not None:
            # executor-resident extraction (featureRDD, :483-505):
            # params come from -weights/-snapshot, no solver thread;
            # blob_names=None resolves daemon-side (net outputs +
            # -label, default_feature_blobs)
            engine.setup(start_training=False)
            try:
                rows = engine.features_source(source, blob_names)
            finally:
                engine.shutdown()
            names = (blob_names if blob_names else
                     [c for c in (rows[0] if rows else {})
                      if c != "SampleID"])
            return DataFrame(rows, ["SampleID"] + list(names))
        proc = CaffeProcessor.instance(conf, rank=conf.rank)
        if blob_names is None:
            blob_names = proc.default_feature_blobs()
        rows = proc.extract_features(source, blob_names)
        return DataFrame(rows, ["SampleID"] + blob_names)

    # ------------------------------------------------------------------
    def _feed_until_done(self, proc: CaffeProcessor,
                         source: DataSource) -> None:
        gen = _record_loop(source,
                           persistent=bool(getattr(proc.conf,
                                                   "isPersistent", False)))
        while proc._thread is not None and proc._thread.is_alive():
            if not proc.feed_queue(0, next(gen)):
                break


def _record_loop(source: DataSource, persistent: bool = False):
    """Endless record generator (the repeated RDD re-feed, :204-227);
    train-phase sources emit a per-epoch shuffled order.  With
    `persistent` (the -persistent flag, sourceRDD.persist analog,
    CaffeOnSpark.scala:206) epoch 0 materializes the decoded records in
    memory and later epochs re-serve them (seeded per-epoch reshuffle)
    instead of re-reading the backing store."""
    epoch = 0
    cache: Optional[List] = [] if persistent else None
    while True:
        n = 0
        if cache and epoch > 0:
            if source.phase_train:
                rng = np.random.RandomState(source.epoch_seed(epoch))
                order = rng.permutation(len(cache))
            else:
                order = range(len(cache))
            for i in order:
                n += 1
                yield cache[i]
        else:
            records = (source.shuffled_records(epoch)
                       if source.phase_train else source.records())
            for rec in records:
                n += 1
                if cache is not None:
                    cache.append(rec)
                yield rec
        if n == 0:
            raise ValueError("data source produced no records")
        epoch += 1


def source_conf(source: DataSource) -> Config:
    conf = getattr(source, "_conf", None)
    if conf is None:
        raise ValueError("pass conf= explicitly (source has none)")
    return conf


def validation_source(conf: Config) -> Optional[DataSource]:
    """Interleaved-validation source, or None if the config doesn't
    interleave.  Every rank feeds the SAME validation data in lockstep
    — the reference replicates the one validation partition to every
    executor (CaffeOnSpark.scala:293-302 via UnionRDDWLocsSpecified
    + Util.executorLocations); rank-sharding it would validate each
    rank on different data, so rank/num_ranks are pinned to 0/1."""
    test_layer = conf.test_data_layer()
    sp = conf.solverParameter
    if test_layer is None or not sp.test_interval \
            or not (sp.test_iter and sp.test_iter[0]):
        return None
    return get_source(test_layer, phase_train=False, rank=0,
                      num_ranks=1, resize=conf.resize)


# ---------------------------------------------------------------------------
# CLI (CaffeOnSpark.main, :27-84)
# ---------------------------------------------------------------------------

def _cli_spark_context(conf: Config):
    """Under spark-submit the reference's main always runs with a
    SparkContext; mirror that when a cluster is requested AND pyspark
    exists.  Local/TPU-pod runs (clusterSize <= 1, or no pyspark) stay
    on the first-class local engine — no JVM required."""
    if conf.clusterSize <= 1:
        return None
    from . import spark as spark_mod
    if not spark_mod.spark_available():
        return None
    from pyspark import SparkContext
    return SparkContext.getOrCreate()


def _serve_sigterm_drains() -> None:
    """Route SIGTERM — and an operator's Ctrl-C — onto the same
    drain-then-exit path.  The fleet/supervisor teardown
    (tools/supervisor.terminate_processes) sends SIGTERM with a grace
    window precisely so accepted serving work can flush; without a
    handler Python's default disposition kills the process instantly
    and the drain never runs.  The flight recorder dumps FIRST — if
    the grace window closes and SIGKILL lands mid-drain, the event
    timeline is already on disk (COS_RECORDER_DUMP).  SIGINT gets the
    same treatment: Python's default KeyboardInterrupt would run the
    drain but dump the ring only at the very end of the finally block
    — a second Ctrl-C mid-drain would lose it, so the dump lands
    before the drain here too."""
    def handler(signum, frame):
        from .obs.recorder import maybe_dump, record
        name = "SIGINT" if signum == signal.SIGINT else "SIGTERM"
        record("serve", "signal", signal=name)
        maybe_dump(name.lower())
        raise KeyboardInterrupt
    try:
        signal.signal(signal.SIGTERM, handler)
        signal.signal(signal.SIGINT, handler)
    except ValueError:
        pass                  # not the main thread (embedded): skip


def _dump_serve_metrics(summary: dict) -> None:
    """COS_SERVE_METRICS=path: one JSON document at shutdown (same
    shape for single-process and fleet mode).  The flight-recorder
    artifact (COS_RECORDER_DUMP) and the trace spool flush land here
    too — the clean-shutdown counterpart of the SIGTERM dump."""
    path = os.environ.get("COS_SERVE_METRICS")
    if path:
        with open(path, "w") as f:
            json.dump(summary, f, indent=2, sort_keys=True)
            f.write("\n")
    from .obs.recorder import maybe_dump
    from .obs.trace import get_tracer
    maybe_dump("shutdown")
    get_tracer().flush_spool()


def serve_fleet_main(conf: Config, replicas: int) -> int:
    """-serve -serveReplicas N: fleet mode.  N replica processes (each
    the unchanged single-process stack on an ephemeral loopback port)
    behind the least-outstanding router; the client-facing port is the
    ROUTER's.  Replica death is absorbed: the router retries onto
    healthy peers while the fleet monitor restarts the dead process
    (warm via COS_AOT_CACHE_DIR when set)."""
    from .serving.fleet import Fleet
    from .serving.router import RouterHTTPServer
    _serve_sigterm_drains()
    serve_args = ["-conf", conf.protoFile]
    if conf.modelPath:
        serve_args += ["-model", conf.modelPath]
    if conf.snapshotModelFile:
        serve_args += ["-weights", conf.snapshotModelFile]
    if conf.snapshotStateFile:
        serve_args += ["-snapshot", conf.snapshotStateFile]
    # the served-blob selection must reach the replicas, or they fall
    # back to the net's output blobs and answer the wrong columns
    if conf.features:
        serve_args += ["-features", conf.features]
    if conf.label:
        serve_args += ["-label", conf.label]
    if getattr(conf, "resize", False):
        serve_args += ["-resize"]
    # sharded serving: each replica builds the same mesh layout
    if getattr(conf, "serveMesh", ""):
        serve_args += ["-serveMesh", conf.serveMesh]
    fleet = Fleet(serve_args, replicas)
    fleet.start()
    try:
        # inside the guard: a bind failure (port in use) must not
        # orphan N freshly-warmed replica subprocesses
        httpd = RouterHTTPServer(fleet.router, host=conf.serveHost,
                                 port=conf.servePort,
                                 reload_fn=fleet.rolling_reload,
                                 publish_fn=fleet.publish_model)
    except BaseException:
        fleet.stop()
        raise
    try:
        # inside the guard: a signal (or BrokenPipeError on a closed
        # stdout) landing during the boot print must still tear the
        # warmed replicas down
        print(json.dumps({"serving": True, "port": httpd.port,
                          "replicas": replicas,
                          "replica_urls": {n: r.url for n, r
                                           in fleet.replicas.items()}}),
              flush=True)
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.stop()
        fleet.stop()
        _dump_serve_metrics(fleet.metrics_summary())
    return 0


def deploy_main(conf: Config) -> int:
    """-deploy mode: the continuous-deployment loop (deploy/).  Runs
    `-deployRounds` (or COS_DEPLOY_ROUNDS) rounds of stream-follow →
    fine-tune → canary → fleet roll/rollback, printing one JSON line
    per round verdict, then dumps the fleet+deploy metrics (info.deploy
    included) to COS_SERVE_METRICS when set."""
    from .deploy import DeployController, deploy_rounds
    _serve_sigterm_drains()
    ctl = DeployController(conf)
    ctl.start()
    try:
        print(json.dumps({"deploying": True,
                          "incumbent": ctl.incumbent,
                          "replicas": ctl.replicas,
                          "stream": ctl.source.describe()}),
              flush=True)
        for r in range(conf.deployRounds or deploy_rounds()):
            rec = ctl.run_round()
            print(json.dumps({"deploy_round": rec["round"],
                              "verdict": rec["verdict"],
                              "reason": rec.get("reason"),
                              "incumbent": rec["incumbent"]}),
                  flush=True)
    except KeyboardInterrupt:
        pass
    finally:
        ctl.stop()
        _dump_serve_metrics(ctl.metrics_summary())
    return 0


def serve_main(conf: Config) -> int:
    """-serve mode: online inference over the serving subsystem.  Runs
    until interrupted; drains in-flight requests on shutdown and dumps
    serving metrics to COS_SERVE_METRICS (same JSON format as the
    pipeline metrics) when set.  `-serveReplicas N` (or
    COS_SERVE_REPLICAS) > 1 switches to fleet mode."""
    from .serving import InferenceService, ServingHTTPServer
    from .serving.fleet import serve_replicas
    n = conf.serveReplicas if getattr(conf, "serveReplicas", 0) > 0 \
        else serve_replicas()
    if n > 1:
        return serve_fleet_main(conf, n)
    _serve_sigterm_drains()
    svc = InferenceService(conf)   # loads -weights, else -model
    svc.start()
    httpd = ServingHTTPServer(svc, host=conf.serveHost,
                              port=conf.servePort)
    try:
        print(json.dumps({"serving": True, "port": httpd.port,
                          "model_version": svc.registry.version,
                          "buckets": list(svc.batcher.buckets)}),
              flush=True)
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
        svc.stop(drain=True)
        _dump_serve_metrics(svc.metrics_summary())
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    conf = Config(argv if argv is not None else sys.argv[1:])
    conf.validate()
    if getattr(conf, "serve", False):
        return serve_main(conf)
    if getattr(conf, "deploy", False):
        return deploy_main(conf)
    cos = CaffeOnSpark(_cli_spark_context(conf))

    if conf.isTraining:
        # the trained model is handed to a later -test/-features phase
        # through the model file, as the reference does via -model
        if not conf.modelPath:
            conf.modelPath = fsutils.join(conf.outputPath or ".",
                                          "model.caffemodel")
        train_layer = conf.train_data_layer()
        src = get_source(train_layer, phase_train=True, rank=conf.rank,
                         num_ranks=max(1, conf.clusterSize),
                         resize=conf.resize)
        src._conf = conf
        val_src = validation_source(conf)
        if val_src is not None:
            df = cos.trainWithValidation(src, val_src, conf)
            if conf.outputPath:
                df.write(fsutils.join(conf.outputPath,
                                      "validation." + conf.outputFormat),
                         conf.outputFormat)
        else:
            cos.train(src, conf)

    if conf.isTest or conf.features:
        # load trained weights: after a training phase the JUST-trained
        # model wins (even over a -weights finetune source); in
        # test/features-only runs, -model supplies the weights
        if conf.isTraining and conf.modelPath \
                and fsutils.exists(conf.modelPath):
            conf.snapshotModelFile = conf.modelPath
            conf.snapshotStateFile = ""
        elif conf.modelPath and fsutils.exists(conf.modelPath) \
                and not conf.snapshotModelFile:
            conf.snapshotModelFile = conf.modelPath
        layer = conf.test_data_layer() or conf.train_data_layer()
        src = get_source(layer, phase_train=False, rank=conf.rank,
                         num_ranks=max(1, conf.clusterSize),
                         resize=conf.resize)
        src._conf = conf
        if conf.isTest:
            result = cos.test(src, conf)
            out = json.dumps(result)
            print(out)
            if conf.outputPath:
                with fsutils.open_file(
                        fsutils.join(conf.outputPath, "test_result"),
                        "w") as f:
                    f.write(out + "\n")
        else:
            df = cos.features(src, conf)
            if conf.outputPath:
                df.write(fsutils.join(conf.outputPath,
                                      "features." + conf.outputFormat),
                         conf.outputFormat)
    return 0


if __name__ == "__main__":
    sys.exit(main())
