"""Spark execution backend (optional — activates when pyspark exists).

The reference's defining integration (SURVEY §3.1): the Spark driver
parallelizes one task per executor, each executor hosts a CaffeProcessor
singleton bound to its accelerators, the driver collects server
addresses, broadcasts the rank→address map, and streams RDD partitions
into the executor feed queues.  Here the same choreography bootstraps a
multi-host JAX mesh instead of socket/RDMA servers:

  1. `sc.parallelize(range(clusterSize), clusterSize)` pins one task per
     executor; task 0's host becomes the `jax.distributed` coordinator
     (the getLocalAddress/collect round, CaffeOnSpark.scala:113-142);
  2. every executor calls `distributed_init(coordinator, N, rank)` and
     builds the global mesh — connect-retry and barrier semantics come
     from the JAX runtime rather than SocketChannel::Connect;
  3. training tasks stream their partition's records into
     `CaffeProcessor.feed_queue` with the same backpressure/STOP_MARK
     protocol (:192-198), under the lockstep step-count invariant
     (`parallel.mesh.lockstep_steps` — the minPartSize barrier analog,
     :185-200);
  4. rank 0 snapshots; results return as Spark DataFrames.

This environment ships no pyspark, so everything importable here is
tested only for the no-spark code paths; `require_spark()` raises an
actionable error otherwise."""

from __future__ import annotations

import socket
from typing import Any, Dict, List

from .config import Config


def spark_available() -> bool:
    try:
        import pyspark  # noqa: F401
        return True
    except ImportError:
        return False


def require_spark():
    if not spark_available():
        raise RuntimeError(
            "pyspark is not installed; use the local engine "
            "(caffe_on_spark.CaffeOnSpark with no SparkContext) or the "
            "standalone trainer (mini_cluster)")
    import pyspark
    return pyspark


def coordinator_port(app_id: str = "", base: int = 47770) -> int:
    """Deterministic jax.distributed coordinator port, varied per Spark
    application so back-to-back jobs on one cluster don't collide."""
    import zlib
    return base + (zlib.crc32(app_id.encode()) % 199)


class SparkEngine:
    """Driver-side engine dispatching CaffeProcessor work to executors.

    Uses Spark **barrier execution** for the mesh bring-up: the barrier
    stage guarantees all `clusterSize` tasks run concurrently (or the
    stage fails fast with Spark's own actionable error — the startup
    executor-count sanity of CaffeOnSpark.scala:127-133), and
    `BarrierTaskContext.getTaskInfos()` provides every task's address —
    the all-gather that replaces the reference's collect round
    (:113-142).  Task 0's host becomes the jax.distributed coordinator;
    the coordinator binds inside rank 0's own `distributed_init`, so the
    advertised host:port is by construction on the right machine."""

    def __init__(self, sc, conf: Config):
        require_spark()
        self.sc = sc
        self.conf = conf
        self.cluster_size = max(1, conf.clusterSize)

    def setup(self) -> List[Dict[str, Any]]:
        """Start processors on every executor, multi-host mesh up."""
        conf_bytes = _pickle_conf(self.conf)
        n = self.cluster_size
        port = coordinator_port(self.sc.applicationId)

        def start(it):
            from pyspark import BarrierTaskContext
            ctx = BarrierTaskContext.get()
            rank = ctx.partitionId()
            infos = ctx.getTaskInfos()
            coord_host = infos[0].address.split(":")[0]
            ctx.barrier()          # everyone resolved the coordinator
            from .parallel import distributed_init
            from .processor import CaffeProcessor
            conf = _unpickle_conf(conf_bytes)
            distributed_init(f"{coord_host}:{port}", n, rank)
            proc = CaffeProcessor.instance(conf, rank=rank)
            proc.start()
            yield {"rank": rank, "host": socket.gethostname()}

        plan = (self.sc.parallelize(range(n), n).barrier()
                .mapPartitions(start).collect())
        assert sorted(p["rank"] for p in plan) == list(range(n))
        return sorted(plan, key=lambda p: p["rank"])

    def feed_partitions(self, rdd, queue_idx: int = 0) -> int:
        """Stream records of each partition into the local processor's
        feed queue (the mapPartitions feed loop, :204-227)."""
        def feed(it):
            from .processor import CaffeProcessor
            proc = CaffeProcessor.instance()
            fed = 0
            for rec in it:
                if not proc.feed_queue(queue_idx, rec):
                    break
                fed += 1
            proc.mark_epoch_end(queue_idx)
            yield fed

        return sum(rdd.mapPartitions(feed).collect())

    def shutdown(self):
        def stop(rank):
            from .processor import CaffeProcessor
            try:
                CaffeProcessor.instance().stop()
            except AssertionError:
                pass
            return rank

        n = self.cluster_size
        self.sc.parallelize(range(n), n).map(stop).collect()


def _pickle_conf(conf: Config) -> bytes:
    import pickle
    state = {k: getattr(conf, k) for k in vars(conf.args)}
    state["protoFile"] = conf.protoFile
    return pickle.dumps(state)


def _unpickle_conf(blob: bytes) -> Config:
    import pickle
    state = pickle.loads(blob)
    return Config([], **state)
