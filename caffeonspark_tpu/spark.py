"""Spark execution backend (optional — activates when pyspark exists).

The reference's defining integration (SURVEY §3.1): the Spark driver
parallelizes one task per executor, each executor hosts a CaffeProcessor
singleton bound to its accelerators, the driver collects server
addresses, broadcasts the rank→address map, and streams RDD partitions
into the executor feed queues.  Here the same choreography bootstraps a
multi-host JAX mesh instead of socket/RDMA servers:

  1. `sc.parallelize(range(clusterSize), clusterSize)` pins one task per
     executor; task 0's host becomes the `jax.distributed` coordinator
     (the getLocalAddress/collect round, CaffeOnSpark.scala:113-142);
  2. every executor calls `distributed_init(coordinator, N, rank)` and
     builds the global mesh — connect-retry and barrier semantics come
     from the JAX runtime rather than SocketChannel::Connect;
  3. training tasks stream their partition's records into
     `CaffeProcessor.feed_queue` with the same backpressure/STOP_MARK
     protocol (:192-198), under the lockstep step-count invariant
     (`parallel.mesh.lockstep_steps` — the minPartSize barrier analog,
     :185-200);
  4. rank 0 snapshots; results return as Spark DataFrames.

This environment ships no pyspark, so everything importable here is
tested only for the no-spark code paths; `require_spark()` raises an
actionable error otherwise."""

from __future__ import annotations

import os
import socket
from typing import Any, Dict, List, Optional

from .config import Config


def spark_available() -> bool:
    try:
        import pyspark  # noqa: F401
    except ImportError:
        return False
    # pyspark without a JVM fails at SparkContext construction with a
    # gateway error, not an ImportError — count that as unavailable so
    # callers/tests skip instead of erroring
    import shutil
    return bool(shutil.which("java")
                or os.environ.get("JAVA_HOME"))


def require_spark():
    if not spark_available():
        raise RuntimeError(
            "pyspark is not installed; use the local engine "
            "(caffe_on_spark.CaffeOnSpark with no SparkContext) or the "
            "standalone trainer (mini_cluster)")
    import pyspark
    return pyspark


def coordinator_port(app_id: str = "", base: int = 47770) -> int:
    """Deterministic jax.distributed coordinator port, varied per Spark
    application so back-to-back jobs on one cluster don't collide."""
    import zlib
    return base + (zlib.crc32(app_id.encode()) % 199)


def _discover_for_task(app_id: str, rank: int, partition_idx: int):
    """Task-side resolution of where records go: the host-local daemon
    for `rank` (strict pinning honored), else the same-process
    CaffeProcessor fallback for local[*] worker reuse.  Returns
    (client, None) or (None, processor); raises actionably otherwise.
    Shared by the feed and features task closures."""
    from .spark_daemon import FeedClient, strict_rank_enabled
    client = FeedClient.discover(app_id, rank=rank)
    if client is not None:
        return client, None
    if strict_rank_enabled():
        raise RuntimeError(
            f"strict rank pinning: no responsive feed daemon for rank "
            f"{rank} on this host (UnionRDDWLocsSpecified contract). "
            f"Either Spark placed partition {partition_idx} on the "
            "wrong executor (relaunch with locality-pinned scheduling) "
            "or that rank's daemon/processor died (check executor "
            "logs); unset COS_FEED_STRICT_RANK to allow any-local "
            "fallback")
    from .processor import CaffeProcessor
    try:
        return None, CaffeProcessor.instance()
    except Exception as e:
        raise RuntimeError(
            "no feed daemon port file and no in-process CaffeProcessor "
            "— was setup() run?") from e


def _get_barrier_context():
    """Indirection point: tests substitute a barrier-context double
    (pyspark doesn't exist in this image)."""
    from pyspark import BarrierTaskContext
    return BarrierTaskContext.get()


def _pickle_source_spec(source) -> bytes:
    """Serializable recipe for reconstructing a DataSource inside a
    Spark task: the layer proto + construction kwargs.  Ships a few
    hundred bytes to the executors instead of the dataset itself."""
    import pickle
    return pickle.dumps({
        "layer": source.layer, "phase_train": source.phase_train,
        "seed": source.seed, "resize": source.resize,
        "num_threads": source.num_threads,
    })


def _task_source(blob: bytes, rank: int, num_ranks: int):
    """Executor-side: open the source's own rank shard (the readers'
    partition_ranges / file-sharding handle the split)."""
    import pickle

    from .data.source import get_source
    spec = pickle.loads(blob)
    return get_source(spec["layer"], phase_train=spec["phase_train"],
                      rank=rank, num_ranks=num_ranks,
                      seed=spec["seed"], resize=spec["resize"],
                      num_threads=spec["num_threads"])


def _feed_records(client, proc, queue_idx: int, records) -> int:
    """Stream records into the rank's feed path — daemon when
    discovered, same-process processor fallback otherwise.  Shared by
    the RDD-partition and executor-side-source feed tasks."""
    if client is not None:
        try:
            fed = client.feed(queue_idx, records)
            client.epoch_end(queue_idx)
        finally:
            client.close()
        return fed
    fed = 0
    for rec in records:
        if not proc.feed_queue(queue_idx, rec):
            break
        fed += 1
    proc.mark_epoch_end(queue_idx)
    return fed


class SparkEngine:
    """Driver-side engine dispatching CaffeProcessor work to executors.

    Uses Spark **barrier execution** for the mesh bring-up: the barrier
    stage guarantees all `clusterSize` tasks run concurrently (or the
    stage fails fast with Spark's own actionable error — the startup
    executor-count sanity of CaffeOnSpark.scala:127-133), and
    `BarrierTaskContext.getTaskInfos()` provides every task's address —
    the all-gather that replaces the reference's collect round
    (:113-142).  Task 0's host becomes the jax.distributed coordinator;
    the coordinator binds inside rank 0's own `distributed_init`, so the
    advertised host:port is by construction on the right machine."""

    def __init__(self, sc, conf: Config, *, require: bool = True):
        if require:
            require_spark()
        self.sc = sc
        self.conf = conf
        self.cluster_size = max(1, conf.clusterSize)

    @property
    def app_id(self) -> str:
        return getattr(self.sc, "applicationId", "") or ""

    def setup(self, *, interleave_validation: bool = False,
              start_training: bool = True) -> List[Dict[str, Any]]:
        """Start processors on every executor, multi-host mesh up.

        Each executor also starts a FeedDaemon (spark_daemon.py): Spark
        feed tasks run in separate Python worker processes that cannot
        see the processor singleton, so records are handed off over a
        host-local socket — the Python-process analog of the
        reference's task-thread→feedQueue sharing
        (CaffeProcessor.scala:192-198)."""
        conf_bytes = _pickle_conf(self.conf)
        n = self.cluster_size
        port = coordinator_port(self.app_id)
        app_id = self.app_id
        interleave = interleave_validation
        training = start_training

        def start(it):
            ctx = _get_barrier_context()
            rank = ctx.partitionId()
            infos = ctx.getTaskInfos()
            coord_host = infos[0].address.split(":")[0]
            ctx.barrier()          # everyone resolved the coordinator
            from .parallel import distributed_init
            from .processor import CaffeProcessor
            from .spark_daemon import FeedDaemon
            conf = _unpickle_conf(conf_bytes)
            if n > 1:
                distributed_init(f"{coord_host}:{port}", n, rank)
            proc = CaffeProcessor.instance(conf, rank=rank)
            proc.interleave_validation = interleave
            if training:
                proc.start()
            else:
                # features/test mode (features2, :445-506): params come
                # from -weights/-snapshot, no solver thread — the daemon
                # serves EXTRACT requests
                proc._init_params()
            proc._feed_daemon = FeedDaemon(proc, app_id, rank=rank)
            yield {"rank": rank, "host": socket.gethostname(),
                   "feed_port": proc._feed_daemon.port}

        plan = (self.sc.parallelize(range(n), n).barrier()
                .mapPartitions(start).collect())
        assert sorted(p["rank"] for p in plan) == list(range(n))
        return sorted(plan, key=lambda p: p["rank"])

    def feed_partitions(self, rdd, queue_idx: int = 0) -> int:
        """Stream records of each partition into the executor-resident
        processor (the mapPartitions feed loop, :204-227).  The task
        discovers the host-local daemon via its port file; the
        same-process singleton is only a fallback for local[*] mode
        with worker reuse."""
        app_id = self.app_id
        n = self.cluster_size

        def feed(idx, it):
            client, proc = _discover_for_task(app_id, idx % n, idx)
            yield _feed_records(client, proc, queue_idx, it)

        return sum(rdd.mapPartitionsWithIndex(feed).collect())

    def feed_source(self, source, queue_idx: int = 0,
                    epoch: int = 0) -> int:
        """One epoch of EXECUTOR-SIDE reads: one task per rank
        reconstructs the source inside the task and streams its own
        rank shard into the host-local daemon.  Records never
        materialize on — or stream through — the driver, matching the
        reference's executor-resident partition reads (LmdbRDD's
        compute() opens the database on the executor,
        LmdbRDD.scala:101-136; the round-4 advisor flagged the
        previous driver-side list(source.records()) as an OOM for
        Caffe-scale databases).  TRAIN-phase shards reshuffle per
        epoch via the source's deterministic (seed, rank, epoch)
        streaming shuffle."""
        app_id = self.app_id
        n = self.cluster_size
        blob = _pickle_source_spec(source)

        def feed(idx, _it):
            rank = idx % n
            src = _task_source(blob, rank, n)
            records = (src.shuffled_records(epoch) if src.phase_train
                       else src.records())
            client, proc = _discover_for_task(app_id, rank, idx)
            yield _feed_records(client, proc, queue_idx, records)

        return sum(self.sc.parallelize(range(n), n)
                   .mapPartitionsWithIndex(feed).collect())

    def features_partitions(self, rdd, blob_names=None):
        """features()/test() over the cluster: each task ships its
        partition's records to the host-local daemon, the
        executor-resident net runs predict, rows come back to the
        driver (featureRDD construction, CaffeOnSpark.scala:483-505).
        Returns the collected rows (SampleID + per-blob lists)."""
        app_id = self.app_id
        n = self.cluster_size
        names = list(blob_names) if blob_names else None

        def extract(idx, it):
            client, proc = _discover_for_task(app_id, idx % n, idx)
            if client is None:
                nm = names or proc.default_feature_blobs()
                yield from proc.extract_rows(it, nm)
                return
            try:
                yield from client.extract(it, names)
            finally:
                client.close()

        return rdd.mapPartitionsWithIndex(extract).collect()

    def features_source(self, source, blob_names=None):
        """features()/test() with EXECUTOR-SIDE reads: each task opens
        its rank shard of the source inside the task and ships records
        straight to the host-local daemon's EXTRACT op — only the
        result rows cross the driver (featureRDD over LmdbRDD's
        executor-side partitions, CaffeOnSpark.scala:483-505)."""
        app_id = self.app_id
        n = self.cluster_size
        blob = _pickle_source_spec(source)
        names = list(blob_names) if blob_names else None

        def extract(idx, _it):
            rank = idx % n
            src = _task_source(blob, rank, n)
            records = src.records()
            client, proc = _discover_for_task(app_id, rank, idx)
            if client is None:
                nm = names or proc.default_feature_blobs()
                yield from proc.extract_rows(records, nm)
                return
            try:
                yield from client.extract(records, names)
            finally:
                client.close()

        return (self.sc.parallelize(range(n), n)
                .mapPartitionsWithIndex(extract).collect())

    def collect_report(self, rank: int = 0) -> Optional[Dict[str, Any]]:
        """Processor progress + validation rows from one executor (the
        validation-DataFrame collect of CaffeOnSpark.scala:344-357).
        Runs a 1-task job that queries the host-local daemon; returns
        {"rank", "alive", "iter", "validation": {names, rounds}} or
        None when no daemon answered."""
        app_id = self.app_id
        n = self.cluster_size

        def query(_):
            from .spark_daemon import FeedClient
            client = FeedClient.discover(app_id, rank=rank)
            if client is None:
                yield None
                return
            try:
                yield client.report()
            finally:
                client.close()

        # fan out one task per rank: daemon discovery is HOST-LOCAL, so
        # a single task landing on the wrong executor host would find
        # the wrong rank's daemon (or none); with n tasks at least one
        # runs where the target daemon lives, and reports carry their
        # rank so the match is exact
        out = [r for r in (self.sc.parallelize(range(n), n)
                           .mapPartitions(query).collect())
               if r is not None]
        for r in out:
            if r.get("rank") == rank:
                return r
        return out[0] if out else None

    def wait_done(self, timeout: float = 600.0,
                  poll: float = 2.0) -> Optional[Dict[str, Any]]:
        """Poll collect_report until the executor's solver thread
        finishes (max_iter reached) or timeout; returns the final
        report.  The driver feeds records separately — this is the
        'solvers finish, then shutdownProcessors' join of
        CaffeOnSpark.scala:227-230."""
        import time
        deadline = time.monotonic() + timeout
        rep = None
        while time.monotonic() < deadline:
            rep = self.collect_report()
            if rep is not None and not rep["alive"]:
                return rep
            time.sleep(poll)
        return rep

    def shutdown(self):
        """Stop every executor's processor + daemon.  Goes through the
        daemon STOP op (works from any worker process); the singleton
        path is only the same-process fallback."""
        app_id = self.app_id

        def stop(rank):
            from .spark_daemon import FeedClient
            stopped = FeedClient.stop_all(app_id)
            if stopped:
                return stopped
            from .processor import CaffeProcessor
            try:
                proc = CaffeProcessor.instance()
                daemon = getattr(proc, "_feed_daemon", None)
                if daemon is not None:
                    daemon.stop()
                proc.stop()
                return 1
            except AssertionError:
                return 0

        n = self.cluster_size
        self.sc.parallelize(range(n), n).map(stop).collect()


def _pickle_conf(conf: Config) -> bytes:
    import pickle
    state = {k: getattr(conf, k) for k in vars(conf.args)}
    state["protoFile"] = conf.protoFile
    return pickle.dumps(state)


def _unpickle_conf(blob: bytes) -> Config:
    import pickle
    state = pickle.loads(blob)
    return Config([], **state)
