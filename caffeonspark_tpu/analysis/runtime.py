"""Runtime trace-safety guards: the dynamic half of coslint.

Static rules catch what the AST shows; these guards catch what only
shows up while running:

  * `RecompileGuard` — counts XLA compilations of watched jitted
    callables (via their compiled-program cache size) and FAILS when
    steady state recompiles.  A recompilation storm is the runtime
    face of COS003 (trace-time host reads) and of shape drift — the
    exact failure classes the fused train loop (PR 4) and the serving
    buckets (PR 5) exist to prevent.  `COS_RECOMPILE_GUARD=1` arms it
    inside Solver and InferenceService; tests use it directly via the
    `recompile_guard` pytest fixture (tests/conftest.py).

  * `poison_donation` — the debug-mode donation poisoner behind
    COS004: after every call of a donating jitted function it
    `.delete()`s the donated input arrays, so use-after-donation
    fails loudly on EVERY backend (CPU ignores donation and would
    otherwise alias silently).  `COS_DONATION_POISON=1`.

  * `LockWitness` — the lock-order/race witness behind COS005's
    stress tests: wraps locks/conditions on live objects, records the
    per-thread acquisition graph, and reports order inversions
    (`a → b` in one thread, `b → a` in another — a latent deadlock
    even when the schedule never trips it).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple


def _env_on(name: str) -> bool:
    return os.environ.get(name, "").lower() not in ("", "0", "false",
                                                    "no")


# ---------------------------------------------------------------- recompile

class RecompileError(RuntimeError):
    """A watched jitted function compiled in steady state."""


class RecompileGuard:
    """Watch jitted callables; fail when steady state recompiles.

    Two phases per watched function:

      * warm-up — compiles are expected (first call per shape); each
        function gets `allow` of them (None = unlimited until
        `mark_steady()`);
      * steady — entered by `mark_steady()` (all watched functions at
        once, e.g. after serving warm-up) or automatically once a
        function exhausts its `allow`; ANY further cache growth raises
        RecompileError naming the function.

    Counting uses the jitted function's `_cache_size()` (one entry per
    compiled (shapes, dtypes, shardings) signature), so the guard adds
    no tracing overhead and never perturbs numerics — parity pins hold
    with the guard armed.  Enforcement is per-call through the wrapper
    returned by `watch`, plus pull-style via `check()` for callers
    that invoke the underlying function directly.
    """

    def __init__(self, name: str = "recompile-guard"):
        self.name = name
        self._lock = threading.Lock()
        # fn name -> [fn, allowance (None = unlimited), steady bool,
        #             baseline cache size at steady entry]
        self._watched: Dict[str, List[Any]] = {}

    @staticmethod
    def _cache_size(fn) -> Optional[int]:
        probe = getattr(fn, "_cache_size", None)
        if probe is None:
            return None
        try:
            return int(probe())
        except Exception:       # noqa: BLE001 — jax internals moved
            return None

    def watch(self, name: str, fn: Callable, *,
              allow: Optional[int] = None) -> Callable:
        """Register `fn` and return a wrapper that enforces after
        every call.  The wrapper is numerically transparent."""
        with self._lock:
            self._watched[name] = [fn, allow, False,
                                   self._cache_size(fn) or 0]

        def guarded(*args, **kwargs):
            out = fn(*args, **kwargs)
            self._check_one(name)
            return out

        guarded.__wrapped__ = fn
        guarded._recompile_guard = self       # introspection for tests
        return guarded

    def compiles(self) -> Dict[str, int]:
        with self._lock:
            return {name: self._cache_size(entry[0]) or 0
                    for name, entry in self._watched.items()}

    def mark_steady(self):
        """Snapshot every watched function's current compile count as
        its steady-state ceiling."""
        with self._lock:
            for entry in self._watched.values():
                entry[2] = True
                entry[3] = self._cache_size(entry[0]) or 0

    def _check_one(self, name: str):
        with self._lock:
            entry = self._watched.get(name)
            if entry is None:
                return
            fn, allow, steady, baseline = entry
            size = self._cache_size(fn)
            if size is None:
                return
            if not steady:
                if allow is not None and size >= allow:
                    entry[2], entry[3] = True, max(size, allow)
                    steady, baseline = True, entry[3]
                else:
                    entry[3] = size
                    return
            if size > baseline:
                # advance the ceiling BEFORE raising: one violation
                # fails one call (the serving flush that paid the
                # compile), not every call after it — cache hits on
                # the already-compiled programs stay healthy
                entry[3] = size
        if size > baseline:
            raise RecompileError(
                f"{self.name}: '{name}' recompiled in steady state "
                f"({size} compiled programs, steady ceiling "
                f"{baseline}) — shape drift or a trace-time host "
                "read (COS003); see docs/architecture.md "
                "'Correctness tooling'")

    def check(self):
        """Pull-style enforcement over every watched function."""
        for name in list(self._watched):
            self._check_one(name)


def maybe_recompile_guard(name: str) -> Optional[RecompileGuard]:
    """A fresh guard when COS_RECOMPILE_GUARD=1, else None — the
    pattern Solver/InferenceService use so the default path carries
    zero overhead."""
    return RecompileGuard(name) if _env_on("COS_RECOMPILE_GUARD") \
        else None


def maybe_guard_jit(guard: Optional[RecompileGuard], name: str,
                    fn: Callable, *, allow: Optional[int] = 1
                    ) -> Callable:
    """Wrap `fn` under `guard` when armed; identity otherwise."""
    if guard is None:
        return fn
    return guard.watch(name, fn, allow=allow)


# ---------------------------------------------------------------- donation

def poison_donation(fn: Callable, donate_argnums: Tuple[int, ...]
                    ) -> Callable:
    """Debug-mode donation poisoner (COS004's runtime teeth): after
    each call, delete every device array that was passed in a donated
    position, so any later use raises jax's deleted-buffer error
    instead of reading stale or aliased memory.  Backends that honor
    donation already invalidated them — this makes the backends that
    DON'T (CPU) behave the same, which is exactly what a debug mode
    wants: the bug reproduces everywhere."""
    import jax

    def poisoned(*args, **kwargs):
        out = fn(*args, **kwargs)
        for pos in donate_argnums:
            if pos >= len(args):
                continue
            for leaf in jax.tree_util.tree_leaves(args[pos]):
                if isinstance(leaf, jax.Array):
                    try:
                        if not leaf.is_deleted():
                            leaf.delete()
                    except Exception:   # noqa: BLE001 — committed donation
                        pass
        return out

    poisoned.__wrapped__ = fn
    return poisoned


def maybe_poison_donation(fn: Callable,
                          donate_argnums: Tuple[int, ...]) -> Callable:
    return poison_donation(fn, donate_argnums) \
        if _env_on("COS_DONATION_POISON") else fn


# ---------------------------------------------------------------- locks

class LockOrderError(RuntimeError):
    """LockWitness.assert_quiet() found order inversions."""


class LockViolation(NamedTuple):
    kind: str            # "inversion"
    thread: str
    held: str            # lock already held
    acquiring: str       # lock being acquired under it
    note: str


class LockWitness:
    """Dynamic lock-order witness (COS005's runtime half).

    Wrap the locks/conditions of live objects with `wrap()` (or
    `witness_attrs()` for instance attributes); every acquisition
    records an edge (held → acquiring) in a global order graph, and an
    edge whose reverse was already seen — from ANY thread — is an
    inversion: two threads can interleave those two call sites into a
    deadlock even if this run never did.  Condition.wait releases the
    held lock, so witnessed conditions drop out of the held set for
    the duration of the wait (no false edge against locks taken by
    the woken path)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._edges: Dict[Tuple[str, str], str] = {}
        self._violations: List[LockViolation] = []
        self._tls = threading.local()

    # -- held-set bookkeeping ------------------------------------------
    def _held(self) -> List[str]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _on_attempt(self, name: str):
        held = self._held()
        tname = threading.current_thread().name
        with self._mu:
            for h in held:
                if h == name:
                    continue
                self._edges.setdefault((h, name), tname)
                first = self._edges.get((name, h))
                if first is not None:
                    self._violations.append(LockViolation(
                        "inversion", tname, h, name,
                        f"'{tname}' acquires {name} under {h}, but "
                        f"'{first}' acquired {h} under {name}"))

    def _on_acquired(self, name: str):
        self._held().append(name)

    def _on_release(self, name: str):
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    # -- wrappers ------------------------------------------------------
    def wrap(self, lock, name: str):
        """Witness a Lock/RLock/Condition; the wrapper is a drop-in
        (context manager + acquire/release [+ wait/notify])."""
        if hasattr(lock, "wait") and hasattr(lock, "notify"):
            return _WitnessedCondition(self, lock, name)
        return _WitnessedLock(self, lock, name)

    def witness_attrs(self, obj, *attrs: str, prefix: str = ""):
        """Replace `obj.<attr>` locks with witnessed wrappers in
        place; returns obj for chaining."""
        base = prefix or type(obj).__name__
        for attr in attrs:
            inner = getattr(obj, attr)
            setattr(obj, attr, self.wrap(inner, f"{base}.{attr}"))
        return obj

    # -- reporting -----------------------------------------------------
    def violations(self) -> List[LockViolation]:
        with self._mu:
            return list(self._violations)

    def edges(self) -> Dict[Tuple[str, str], str]:
        with self._mu:
            return dict(self._edges)

    def assert_quiet(self):
        v = self.violations()
        if v:
            lines = "; ".join(x.note for x in v[:5])
            raise LockOrderError(
                f"lock-order witness recorded {len(v)} "
                f"inversion(s): {lines}")


class _WitnessedLock:
    def __init__(self, witness: LockWitness, inner, name: str):
        self._w = witness
        self._inner = inner
        self._name = name

    def acquire(self, *args, **kwargs):
        self._w._on_attempt(self._name)
        got = self._inner.acquire(*args, **kwargs)
        if got:
            self._w._on_acquired(self._name)
        return got

    def release(self):
        self._inner.release()
        self._w._on_release(self._name)

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class _WitnessedCondition(_WitnessedLock):
    """Condition wrapper: wait() releases the underlying lock, so the
    held-set must drop the name for the wait's duration."""

    def wait(self, timeout: Optional[float] = None):
        self._w._on_release(self._name)
        try:
            return self._inner.wait(timeout)
        finally:
            self._w._on_acquired(self._name)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        self._w._on_release(self._name)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            self._w._on_acquired(self._name)

    def notify(self, n: int = 1):
        self._inner.notify(n)

    def notify_all(self):
        self._inner.notify_all()
