"""coslint core: module loading, suppressions, baseline, reporting.

The linter is deliberately dependency-free (stdlib `ast` only) so it
runs in the same minimal container as the tests.  A rule receives a
parsed `ModuleCtx` and yields `Finding`s; this module owns everything
around the rules — which files to walk, how `# coslint: disable=`
comments scope, and how findings compare against the checked-in
baseline (`artifacts/coslint_baseline.json`).

Suppression scopes:

  * line  — `# coslint: disable=COS001 -- reason` on the flagged line
    suppresses the named rule(s) for that line only;
  * block — the same comment on a `def` / `class` / `with` header line
    suppresses the rule(s) for the whole statement's body (the header
    is where reviewers look for the reason);
  * file  — `# coslint: disable-file=COS003 -- reason` anywhere in the
    module suppresses the rule(s) module-wide.

`disable=all` is accepted but discouraged — the baseline exists so
every live suppression names the rule it silences and why.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

_DISABLE_RE = re.compile(
    r"#\s*coslint:\s*(disable|disable-file)\s*=\s*"
    r"([A-Za-z0-9_, ]+?|all)\s*(?:--|$)")

# directories never linted even when inside a target path
_SKIP_DIRS = {"__pycache__", ".git", "build", "_html"}


@dataclass(frozen=True)
class Finding:
    """One rule violation.  Baseline identity is (rule, path, message)
    — line/col are for humans and drift with edits, so they stay out
    of the key."""
    rule: str
    path: str              # repo-relative (or as-given) posix path
    line: int
    col: int
    message: str

    @property
    def key(self) -> str:
        return f"{self.rule}:{self.path}:{self.message}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} {self.message}")


class ModuleCtx:
    """Parsed module handed to rules: source, AST, parent links, and
    the suppression table."""

    def __init__(self, path: str, source: str, rel: Optional[str] = None):
        self.path = path
        self.rel = rel or path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self._line_disable: Dict[int, Set[str]] = {}
        self._file_disable: Set[str] = set()
        self._parse_suppressions()
        # block scopes: a disable on a def/class/with header covers the
        # statement's whole [lineno, end_lineno] range
        self._block_disable: List[Tuple[int, int, Set[str]]] = []
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.With)):
                rules = self._line_disable.get(node.lineno)
                if rules:
                    self._block_disable.append(
                        (node.lineno, node.end_lineno or node.lineno,
                         rules))

    def _parse_suppressions(self):
        # real COMMENT tokens only — the syntax quoted inside a string
        # or docstring (e.g. this very module's) must not register
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.source).readline))
        except (tokenize.TokenError, IndentationError):
            tokens = []
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _DISABLE_RE.search(tok.string)
            if not m:
                continue
            kind, names = m.group(1), m.group(2)
            rules = {r.strip().upper() for r in names.split(",")
                     if r.strip()}
            if kind == "disable-file":
                self._file_disable |= rules
            else:
                self._line_disable.setdefault(
                    tok.start[0], set()).update(rules)

    def suppressed(self, rule: str, line: int) -> bool:
        if "ALL" in self._file_disable or rule in self._file_disable:
            return True
        at = self._line_disable.get(line, ())
        if "ALL" in at or rule in at:
            return True
        for lo, hi, rules in self._block_disable:
            if lo <= line <= hi and ("ALL" in rules or rule in rules):
                return True
        return False

    # -- shared AST helpers used by several rules ----------------------
    def enclosing(self, node: ast.AST, kinds) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, kinds):
                return cur
            cur = self.parents.get(cur)
        return None

    def enclosing_class_name(self, node: ast.AST) -> str:
        cls = self.enclosing(node, ast.ClassDef)
        return cls.name if cls is not None else ""


def dotted(node: ast.AST) -> str:
    """`jax.device_put` / `self._q.put` → the dotted source string;
    '' for anything that is not a plain Name/Attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def own_nodes(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk a function/module body WITHOUT descending into nested
    function/class definitions — each def is its own rule scope."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def scopes(ctx: ModuleCtx):
    """Every rule scope in the module: the module body plus each
    (possibly nested) function def."""
    yield ctx.tree
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def shares_loop(ctx: ModuleCtx, a: ast.AST, b: ast.AST,
                scope: ast.AST) -> bool:
    """True when a and b sit under one loop inside `scope` — textual
    order then says nothing about execution order (the reused-buffer
    pattern: mutate on the NEXT iteration)."""
    def loop_ancestors(n):
        out = []
        cur = ctx.parents.get(n)
        while cur is not None and cur is not scope:
            if isinstance(cur, (ast.For, ast.While, ast.AsyncFor)):
                out.append(cur)
            cur = ctx.parents.get(cur)
        return out

    la, lb = loop_ancestors(a), loop_ancestors(b)
    return any(x in lb for x in la)


# ---------------------------------------------------------------- run

@dataclass
class LintResult:
    findings: List[Finding]
    suppressed: int
    files: int

    @property
    def clean(self) -> bool:
        return not self.findings


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def default_target() -> str:
    """The package itself — `python -m caffeonspark_tpu.analysis` with
    no arguments lints the whole ~25-module tree."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_lint(paths: Optional[Sequence[str]] = None, *,
             rules=None, rel_root: Optional[str] = None) -> LintResult:
    from .rules import ALL_RULES
    rules = list(rules) if rules is not None else \
        [r() for r in ALL_RULES]
    if not paths:
        paths = [default_target()]
        rel_root = rel_root or os.path.dirname(paths[0])
    findings: List[Finding] = []
    suppressed = 0
    files = 0
    for path in iter_py_files(paths):
        rel = (os.path.relpath(path, rel_root).replace(os.sep, "/")
               if rel_root else path.replace(os.sep, "/"))
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
            ctx = ModuleCtx(path, source, rel=rel)
        except (SyntaxError, UnicodeDecodeError) as e:
            findings.append(Finding(
                "COS000", rel, getattr(e, "lineno", 1) or 1, 0,
                f"unparseable module: {e.__class__.__name__}"))
            continue
        files += 1
        seen: Set[Tuple[str, int, int, str]] = set()
        for rule in rules:
            for f in rule.check(ctx):
                ident = (f.rule, f.line, f.col, f.message)
                if ident in seen:       # e.g. nested attribute nodes
                    continue
                seen.add(ident)
                if ctx.suppressed(f.rule, f.line):
                    suppressed += 1
                else:
                    findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintResult(findings=findings, suppressed=suppressed,
                      files=files)


# ---------------------------------------------------------------- baseline

def baseline_keys(findings: Iterable[Finding]) -> Set[str]:
    return {f.key for f in findings}


def load_baseline(path: str) -> Set[str]:
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    return {f["rule"] + ":" + f["path"] + ":" + f["message"]
            for f in doc.get("findings", [])}


def write_baseline(path: str, result: LintResult):
    doc = {
        "version": 1,
        "note": ("coslint baseline: findings listed here are known and "
                 "tolerated; the tier-1 gate fails on anything NOT in "
                 "this list.  Kept at zero findings — fix or suppress "
                 "with a reasoned `# coslint: disable=` instead of "
                 "baselining."),
        "files_scanned": result.files,
        "suppressed_in_source": result.suppressed,
        "findings": [{"rule": f.rule, "path": f.path,
                      "message": f.message} for f in result.findings],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
