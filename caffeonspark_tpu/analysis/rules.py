"""coslint rules COS001..COS005.

Each rule is an AST pass with an ID, a docstring stating exactly what
it catches (and what it deliberately does not), and a worked known-bad
example in tests/fixtures/coslint/.  The rules are tuned for THIS
codebase's bug history — they prefer few, high-confidence findings
over exhaustive dataflow analysis, because the tier-1 gate runs them
on every test invocation and a noisy rule would train people to
suppress reflexively.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .coslint import (Finding, ModuleCtx, dotted, own_nodes, scopes,
                      shares_loop)


class Rule:
    """Base: subclasses set `id`/`title` and implement check(ctx)."""

    id = "COS000"
    title = "abstract rule"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: ModuleCtx, node: ast.AST,
                message: str) -> Finding:
        return Finding(self.id, ctx.rel, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), message)


def _ordered(node: ast.AST) -> Tuple[int, int]:
    return (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))


def _is_f32_dtype(node: ast.AST) -> bool:
    d = dotted(node)
    if d.endswith("float32"):
        return True
    return (isinstance(node, ast.Constant)
            and node.value in ("float32", "f32"))


def _has_f32_cast(node: ast.AST) -> bool:
    """Does this expression subtree contain an explicit f32 upcast —
    `x.astype(jnp.float32)`, `jnp.asarray(x, jnp.float32)`,
    `jnp.array(x, dtype=np.float32)`?"""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        fn = sub.func
        if isinstance(fn, ast.Attribute) and fn.attr == "astype":
            if sub.args and _is_f32_dtype(sub.args[0]):
                return True
        name = dotted(fn)
        if name.split(".")[-1] in ("asarray", "array", "full", "zeros",
                                   "ones"):
            if any(_is_f32_dtype(a) for a in sub.args[1:]):
                return True
            if any(kw.arg == "dtype" and _is_f32_dtype(kw.value)
                   for kw in sub.keywords):
                return True
    return False


class DevicePutAliasing(Rule):
    """COS001 — host buffer staged with `jax.device_put` and mutated
    afterwards.

    On the CPU backend `device_put` ALIASES aligned host numpy buffers
    (zero-copy), so mutating the source buffer after staging corrupts
    the staged batch — the PR 3 ingest bug (see queue_runner.py's
    `_resolve_host_copy`).  Flagged: a `device_put(buf, ...)` (or
    `make_array_from_process_local_data(..., buf)`) whose buffer is a
    plain name/attribute that the same scope later mutates in place
    (`buf[...] = `, `buf += `, `buf.fill/sort/partition/resize(...)`,
    `np.copyto(buf, ...)`) — "later" includes any mutation sharing a
    loop with the put, the classic reused-pack-buffer shape.  Not
    flagged: staging a fresh copy (`np.array(x, copy=True)`,
    `x.copy()`) or rebinding the name before mutating.
    """

    id = "COS001"
    title = "device_put of a host buffer that is later mutated"

    _MUTATORS = {"fill", "sort", "partition", "resize", "itemset",
                 "setflags", "setfield", "byteswap"}

    def _put_buffer(self, call: ast.Call) -> Optional[ast.AST]:
        name = dotted(call.func)
        leaf = name.split(".")[-1]
        if leaf == "device_put" and call.args:
            return call.args[0]
        if leaf == "make_array_from_process_local_data":
            if len(call.args) >= 2:
                return call.args[1]
        return None

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        for scope in scopes(ctx):
            puts: List[Tuple[ast.Call, str]] = []
            mutations: Dict[str, List[ast.AST]] = {}
            rebinds: Dict[str, List[ast.AST]] = {}
            for node in own_nodes(scope):
                if isinstance(node, ast.Call):
                    buf = self._put_buffer(node)
                    if buf is not None:
                        target = dotted(buf)
                        if target:
                            puts.append((node, target))
                    fname = dotted(node.func)
                    if fname.split(".")[-1] == "copyto" and node.args:
                        t = dotted(node.args[0])
                        if t:
                            mutations.setdefault(t, []).append(node)
                    if (isinstance(node.func, ast.Attribute)
                            and node.func.attr in self._MUTATORS):
                        t = dotted(node.func.value)
                        if t:
                            mutations.setdefault(t, []).append(node)
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    for tgt in targets:
                        if isinstance(tgt, ast.Subscript):
                            t = dotted(tgt.value)
                            if t:
                                mutations.setdefault(t, []).append(node)
                        elif isinstance(tgt, (ast.Name, ast.Attribute)):
                            t = dotted(tgt)
                            if t:
                                if isinstance(node, ast.AugAssign):
                                    mutations.setdefault(t, []).append(
                                        node)
                                else:
                                    rebinds.setdefault(t, []).append(
                                        node)
            for call, target in puts:
                for mut in mutations.get(target, ()):
                    if (_ordered(mut) > _ordered(call)
                            or shares_loop(ctx, call, mut, scope)):
                        # a rebind between put and mutation detaches
                        # the name from the staged buffer
                        if any(_ordered(call) < _ordered(rb)
                               < _ordered(mut)
                               for rb in rebinds.get(target, ())):
                            continue
                        yield self.finding(
                            ctx, call,
                            f"host buffer '{target}' is staged with "
                            "device_put and mutated afterwards — on "
                            "the CPU backend device_put aliases the "
                            "host buffer (copy first: np.array(x, "
                            "copy=True), see COS_STAGE_COPY)")
                        break


class EinsumPrecision(Rule):
    """COS002 — f32-consuming contraction without an explicit
    precision.

    On TPU, `jnp.einsum`/`dot`/`matmul` with f32 inputs default to
    bf16 MXU passes: a call site that explicitly upcasts an operand to
    float32 is *declaring* an f32-consuming path, and leaving
    `precision=`/`preferred_element_type=` unset silently throws that
    precision away — the PR 5 sp.py ring-backward bug (fixed by
    forcing HIGHEST on the p/ds-consuming einsums).  Flagged: a
    jnp/lax contraction call with no precision-related kwarg where an
    operand (inline or via a local assigned from a cast in the same
    scope) carries an explicit f32 upcast.  Not flagged: contractions
    whose operands never state f32 intent — default-precision bf16 is
    a legitimate speed choice there.
    """

    id = "COS002"
    title = "f32-consuming einsum/dot/matmul without precision="

    _CONTRACTIONS = {"einsum", "dot", "matmul", "tensordot",
                     "dot_general", "vdot", "inner"}

    def _is_contraction(self, call: ast.Call) -> bool:
        name = dotted(call.func)
        if "." not in name:
            return False
        head, leaf = name.split(".", 1)[0], name.split(".")[-1]
        return (leaf in self._CONTRACTIONS
                and head in ("jnp", "jax", "lax"))

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        for scope in scopes(ctx):
            f32_names: Set[str] = set()
            calls: List[ast.Call] = []
            for node in own_nodes(scope):
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and _has_f32_cast(node.value)):
                    f32_names.add(node.targets[0].id)
                if isinstance(node, ast.Call) and \
                        self._is_contraction(node):
                    calls.append(node)
            for call in calls:
                kws = {kw.arg for kw in call.keywords}
                if kws & {"precision", "preferred_element_type"}:
                    continue
                f32 = False
                for arg in call.args:
                    if _has_f32_cast(arg):
                        f32 = True
                    elif (isinstance(arg, ast.Name)
                          and arg.id in f32_names):
                        f32 = True
                if f32:
                    leaf = dotted(call.func).split(".")[-1]
                    yield self.finding(
                        ctx, call,
                        f"{leaf} consumes an explicit float32 upcast "
                        "but sets no precision= / "
                        "preferred_element_type= — on TPU the MXU "
                        "defaults to bf16 passes and silently drops "
                        "the upcast (force HIGHEST, as in "
                        "parallel/sp.py's ring backward)")


class TraceHostReads(Rule):
    """COS003 — host-side nondeterminism inside traced code.

    A function traced by `jax.jit` / `lax.scan` / `jax.custom_vjp`
    runs ONCE at trace time: `os.environ` / `time.*` / Python or numpy
    `random` calls bake a single host value into the compiled program
    (silently stale forever after), and `.item()` / `float()` on a
    tracer either crashes or forces a sync.  Flagged, inside any
    function reachable from a trace entry in the same module:
    `os.environ[...]`/`os.getenv`, `time.*()` calls, `random.*` /
    `np.random.*` calls (jax.random is fine — it is traced), `.item()`
    calls, and `float()/int()/bool()` applied directly to a function
    parameter.  Trace entries: functions decorated with or passed (by
    name) to jit/pjit/scan/cond/while_loop/fori_loop/vmap/pmap/grad/
    value_and_grad/custom_vjp/defvjp/remat/checkpoint/pallas_call,
    plus functions RETURNED by a factory whose result is jitted
    (`jax.jit(self.train_step_fn())`).  Reachability is per-module by
    design — cross-module trace flows are covered by wiring the
    runtime RecompileGuard at the jit boundaries instead.
    """

    id = "COS003"
    title = "host nondeterminism or env read inside traced code"

    _TRACERS = {"jit", "pjit", "scan", "cond", "while_loop",
                "fori_loop", "vmap", "pmap", "grad", "value_and_grad",
                "custom_vjp", "custom_jvp", "remat", "checkpoint",
                "defvjp", "defjvp", "pallas_call", "shard_map",
                "associative_scan", "switch"}

    def _local_defs(self, ctx: ModuleCtx) -> Dict[str, List[ast.AST]]:
        defs: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)
        return defs

    def _roots(self, ctx: ModuleCtx,
               defs: Dict[str, List[ast.AST]]) -> Set[ast.AST]:
        roots: Set[ast.AST] = set()

        def mark(name: str):
            for d in defs.get(name, ()):
                roots.add(d)

        def returned_defs(factory: ast.AST):
            nested = {n.name for n in ast.walk(factory)
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))
                      and n is not factory}
            for node in ast.walk(factory):
                if (isinstance(node, ast.Return)
                        and isinstance(node.value, ast.Name)
                        and node.value.id in nested):
                    mark(node.value.id)

        # decorators
        for name, nodes in defs.items():
            for d in nodes:
                for dec in d.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) \
                        else dec
                    leaf = dotted(target).split(".")[-1]
                    if leaf in self._TRACERS or leaf == "partial":
                        inner = ""
                        if isinstance(dec, ast.Call) and dec.args:
                            inner = dotted(dec.args[0]).split(".")[-1]
                        if leaf != "partial" or inner in self._TRACERS:
                            roots.add(d)
        # call sites: jit(f), scan(body, ...), f.defvjp(fwd, bwd), and
        # the factory pattern jit(self.make_step()(...)) → the defs the
        # factory returns
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = dotted(node.func).split(".")[-1]
            if leaf not in self._TRACERS:
                continue
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    mark(arg.id)
                elif isinstance(arg, ast.Attribute):
                    mark(arg.attr)
                elif isinstance(arg, ast.Call):
                    factory = dotted(arg.func).split(".")[-1]
                    for d in defs.get(factory, ()):
                        returned_defs(d)
        return roots

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        defs = self._local_defs(ctx)
        roots = self._roots(ctx, defs)
        reachable = set(roots)
        frontier = list(reachable)
        while frontier:
            fn = frontier.pop()
            for node in own_nodes(fn):
                name = ""
                if isinstance(node, ast.Name):
                    name = node.id
                elif isinstance(node, ast.Attribute):
                    name = node.attr
                if not name:
                    continue
                for d in defs.get(name, ()):
                    if d not in reachable:
                        reachable.add(d)
                        frontier.append(d)
        for fn in sorted(reachable, key=_ordered):
            # float()/int() on a parameter is only a confident tracer
            # concretization for trace ROOTS (jit/scan bodies get
            # tracers as params); transitively-reachable helpers often
            # take host-side config values too
            params = ({a.arg for a in fn.args.args
                       + fn.args.posonlyargs + fn.args.kwonlyargs}
                      if fn in roots else set())
            for node in own_nodes(fn):
                yield from self._check_node(ctx, fn, node, params)

    def _check_node(self, ctx: ModuleCtx, fn: ast.AST, node: ast.AST,
                    params: Set[str]) -> Iterator[Finding]:
        where = f"'{fn.name}' is trace-reachable"
        if isinstance(node, (ast.Attribute, ast.Subscript)):
            d = dotted(node if isinstance(node, ast.Attribute)
                       else node.value)
            if d.startswith("os.environ"):
                yield self.finding(
                    ctx, node,
                    f"os.environ read inside traced code ({where}) — "
                    "the value is baked into the compiled program at "
                    "trace time; hoist it to construction/plan time")
                return
        if not isinstance(node, ast.Call):
            return
        d = dotted(node.func)
        leaf = d.split(".")[-1]
        if d == "os.getenv":
            yield self.finding(
                ctx, node,
                f"os.getenv inside traced code ({where}) — hoist the "
                "env read out of the traced function")
        elif d.startswith("time."):
            yield self.finding(
                ctx, node,
                f"host clock call {d}() inside traced code ({where}) "
                "— trace-time timestamps are frozen into the program")
        elif (d.startswith("random.")
              or d.startswith("np.random.")
              or d.startswith("numpy.random.")):
            yield self.finding(
                ctx, node,
                f"host RNG call {d}() inside traced code ({where}) — "
                "draws once at trace time; use jax.random with a "
                "threaded key")
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr == "item" and not node.args):
            yield self.finding(
                ctx, node,
                f".item() inside traced code ({where}) — forces a "
                "host sync / fails on tracers; keep values on device")
        elif (leaf in ("float", "int", "bool") and "." not in d
              and len(node.args) == 1
              and isinstance(node.args[0], ast.Name)
              and node.args[0].id in params):
            yield self.finding(
                ctx, node,
                f"{leaf}() on traced argument "
                f"'{node.args[0].id}' ({where}) — concretizes a "
                "tracer; use jnp casts instead")


class DonationUseAfter(Rule):
    """COS004 — buffer used after being passed to a donating call.

    `jax.jit(..., donate_argnums=...)` hands the argument's buffer to
    XLA: after the call the array is deleted (TPU) or silently aliased
    (backends that ignore donation) — reading it is either a crash or
    a heisenbug.  Flagged: within one scope, a name assigned from
    `jax.jit(..., donate_argnums=...)` is called, and a donated
    positional arg (a plain name) is read again afterwards without
    being rebound.  The runtime counterpart is the COS_DONATION_POISON
    wrapper (analysis/runtime.py), which deletes donated buffers after
    every call so cross-module violations fail loudly in debug runs.
    """

    id = "COS004"
    title = "use of a buffer after donation"

    def _donating_assigns(self, scope) -> Dict[str, Tuple[int, ...]]:
        out: Dict[str, Tuple[int, ...]] = {}
        for node in own_nodes(scope):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)):
                continue
            call = node.value
            if dotted(call.func).split(".")[-1] not in ("jit", "pjit"):
                continue
            for kw in call.keywords:
                if kw.arg != "donate_argnums":
                    continue
                nums: List[int] = []
                for el in ast.walk(kw.value):
                    if isinstance(el, ast.Constant) and \
                            isinstance(el.value, int):
                        nums.append(el.value)
                if nums:
                    out[node.targets[0].id] = tuple(nums)
        return out

    def _stmt_pos(self, ctx: ModuleCtx, node: ast.AST) -> Tuple[int, int]:
        """Position of the enclosing STATEMENT — all of a statement's
        argument reads happen before its call executes and before its
        assignment targets bind, so ordering is (statement position,
        read < donate < rebind)."""
        cur = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = ctx.parents.get(cur)
        return _ordered(cur if cur is not None else node)

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        for scope in scopes(ctx):
            donating = self._donating_assigns(scope)
            if not donating:
                continue
            ranks = {"read": 0, "donate": 1, "rebind": 2}
            events: List[Tuple[Tuple[int, int], int, str, ast.AST]] = []
            loops_of: Dict[str, List[ast.AST]] = {}
            for node in own_nodes(scope):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id in donating):
                    for pos in donating[node.func.id]:
                        if pos < len(node.args) and \
                                isinstance(node.args[pos], ast.Name):
                            events.append(
                                (self._stmt_pos(ctx, node),
                                 ranks["donate"], "donate",
                                 node.args[pos]))
                elif isinstance(node, ast.Name):
                    kind = ("rebind"
                            if isinstance(node.ctx, ast.Store)
                            else "read")
                    events.append((self._stmt_pos(ctx, node),
                                   ranks[kind], kind, node))
                    if kind == "rebind":
                        loops_of.setdefault(node.id, []).append(node)
            events.sort(key=lambda e: (e[0], e[1]))
            live: Dict[str, ast.AST] = {}
            flagged: Set[str] = set()
            for _, _, kind, node in events:
                name = node.id
                if kind == "donate":
                    live.setdefault(name, node)
                    # donating inside a loop without rebinding the name
                    # in that loop: iteration 2 reads a donated buffer
                    if name not in flagged and not any(
                            shares_loop(ctx, node, rb, scope)
                            for rb in loops_of.get(name, ())):
                        if ctx.enclosing(node, (ast.For, ast.While,
                                                ast.AsyncFor)):
                            flagged.add(name)
                            yield self.finding(
                                ctx, node,
                                f"'{name}' is donated inside a loop "
                                "but never rebound there — the next "
                                "iteration reads a deleted/aliased "
                                "buffer; rebind it from the call's "
                                "result")
                elif kind == "rebind":
                    live.pop(name, None)
                elif kind == "read" and name in live and \
                        name not in flagged:
                    flagged.add(name)
                    yield self.finding(
                        ctx, node,
                        f"'{name}' is read after being donated to a "
                        "jit(donate_argnums=...) call — the buffer "
                        "is deleted or aliased by XLA; rebind the "
                        "name from the call's result (or drop the "
                        "donation)")


class LockAcrossBlocking(Rule):
    """COS005 — lock held across a blocking call, and lock-order
    inversions.

    The threaded runtime (serving/batcher.py, the ingest
    TransformerPool, mini_cluster.py, spark_daemon.py) follows one
    discipline: a lock protects STATE TRANSITIONS, never waits.  A
    blocking call under a lock (queue get/put, FeedQueue take/offer,
    Event.wait, socket I/O, thread join, sleep) turns backpressure
    into deadlock the moment the unblocker needs the same lock.
    Flagged: inside a `with <lock>` body — where <lock> is named
    *lock*/*cond*/*mutex* or assigned from threading.Lock/RLock/
    Condition/Semaphore — calls to `.get`/`.put` on queue-like
    receivers (or with timeout=/block=), `.take`/`.offer`, `.wait` on
    anything OTHER than the held lock (Condition.wait on the held
    condition releases it and is fine), `.join` on thread-like
    receivers, `time.sleep`, and socket send/recv/accept/connect.
    Also flagged: two functions acquiring the same pair of locks in
    opposite nesting orders (the cross-function deadlock witness; the
    runtime LockWitness catches the dynamic version in stress tests).
    """

    id = "COS005"
    title = "lock held across a blocking call / lock-order inversion"

    _LOCK_NAME = ("lock", "mutex", "cond", "condition", "sem")
    _LOCK_CTORS = ("Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore")
    _QUEUE_NAME = ("q", "queue", "work", "outq", "inq", "feed",
                   "results")
    _THREAD_NAME = ("thread", "proc", "process", "worker", "stager",
                    "dispatcher", "reader", "snapshotter")
    _SOCKET_OPS = ("recv", "recvfrom", "send", "sendall", "accept",
                   "connect")

    def _lock_attrs(self, ctx: ModuleCtx) -> Set[str]:
        """Names assigned from threading lock constructors —
        class-qualified for self.* attributes so two classes' _lock
        fields stay distinct."""
        out: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            leaf = dotted(node.value.func).split(".")[-1]
            if leaf not in self._LOCK_CTORS:
                continue
            for tgt in node.targets:
                d = dotted(tgt)
                if d:
                    out.add(self._qualify(ctx, node, d))
        return out

    def _qualify(self, ctx: ModuleCtx, node: ast.AST, d: str) -> str:
        if d.startswith("self."):
            cls = ctx.enclosing_class_name(node)
            return f"{cls}.{d[5:]}" if cls else d
        return d

    def _looks_like_lock(self, ctx: ModuleCtx, node: ast.AST,
                         expr: ast.AST, known: Set[str]) -> str:
        d = dotted(expr)
        if not d:
            return ""
        q = self._qualify(ctx, node, d)
        leaf = d.split(".")[-1].lower()
        if q in known or any(k in leaf for k in self._LOCK_NAME):
            return q
        return ""

    def _name_matches(self, receiver: str,
                      pats: Sequence[str]) -> bool:
        leaf = receiver.split(".")[-1].lower().strip("_")
        return any(p == leaf or p in leaf for p in pats)

    def _blocking(self, call: ast.Call, held: List[str],
                  ctx: ModuleCtx) -> str:
        """Return a description if this call can block, else ''."""
        fn = call.func
        d = dotted(fn)
        if d == "time.sleep":
            return "time.sleep()"
        if not isinstance(fn, ast.Attribute):
            return ""
        recv = dotted(fn.value)
        attr = fn.attr
        kwargs = {kw.arg for kw in call.keywords}
        if attr in ("get", "put"):
            if self._name_matches(recv, self._QUEUE_NAME) \
                    or kwargs & {"timeout", "block"}:
                return f"{recv}.{attr}()"
        if attr in ("take", "offer"):
            return f"{recv}.{attr}()"
        if attr == "wait":
            q = self._qualify(ctx, call, recv)
            if q not in held:
                return f"{recv}.wait()"
        if attr == "join" and self._name_matches(recv,
                                                 self._THREAD_NAME):
            return f"{recv}.join()"
        if attr in self._SOCKET_OPS:
            return f"{recv}.{attr}()"
        nonblocking = (
            (call.args and isinstance(call.args[0], ast.Constant)
             and call.args[0].value is False)
            or any(kw.arg == "blocking"
                   and isinstance(kw.value, ast.Constant)
                   and kw.value.value is False
                   for kw in call.keywords))
        if attr == "acquire" and not nonblocking:
            q = self._qualify(ctx, call, recv)
            if q not in held and self._looks_like_lock(
                    ctx, call, fn.value, set()):
                return f"{recv}.acquire()"
        return ""

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        known = self._lock_attrs(ctx)
        # edges[(outer, inner)] = (scope_name, with_node)
        edges: Dict[Tuple[str, str], Tuple[str, ast.AST]] = {}
        for scope in scopes(ctx):
            sname = getattr(scope, "name", "<module>")
            yield from self._walk_body(
                ctx, scope, list(ast.iter_child_nodes(scope)), [],
                known, edges, sname)
        for (a, b), (fn_a, node_a) in sorted(
                edges.items(), key=lambda kv: _ordered(kv[1][1])):
            if (b, a) in edges and a < b:
                fn_b, node_b = edges[(b, a)]
                yield self.finding(
                    ctx, node_a,
                    f"lock-order inversion: '{fn_a}' acquires "
                    f"{a} then {b}, but '{fn_b}' (line "
                    f"{node_b.lineno}) acquires {b} then {a} — "
                    "pick one order (deadlock witness)")

    def _walk_body(self, ctx: ModuleCtx, scope, nodes: List[ast.AST],
                   held: List[str], known: Set[str],
                   edges: Dict, sname: str) -> Iterator[Finding]:
        for node in nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(node, ast.With):
                acquired: List[str] = []
                for item in node.items:
                    lk = self._looks_like_lock(
                        ctx, node, item.context_expr, known)
                    if lk:
                        for h in held + acquired:
                            if h != lk:
                                edges.setdefault((h, lk),
                                                 (sname, node))
                        acquired.append(lk)
                yield from self._walk_body(
                    ctx, scope, node.body, held + acquired, known,
                    edges, sname)
                continue
            if held and isinstance(node, ast.Call):
                why = self._blocking(node, held, ctx)
                if why:
                    yield self.finding(
                        ctx, node,
                        f"blocking call {why} while holding "
                        f"{held[-1]} — waits must happen outside "
                        "the lock (or via Condition.wait on the "
                        "held condition)")
            yield from self._walk_body(
                ctx, scope, list(ast.iter_child_nodes(node)), held,
                known, edges, sname)


ALL_RULES = (DevicePutAliasing, EinsumPrecision, TraceHostReads,
             DonationUseAfter, LockAcrossBlocking)
