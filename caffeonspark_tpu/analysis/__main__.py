"""CLI: `python -m caffeonspark_tpu.analysis [paths...]`.

Exit codes: 0 = clean (or everything baselined), 1 = non-baselined
findings, 2 = bad usage.  `make lint` runs this against the package
with the checked-in baseline; tests/test_coslint.py runs the same
check inside the tier-1 suite.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .coslint import (baseline_keys, load_baseline, run_lint,
                      write_baseline)
from .rules import ALL_RULES


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m caffeonspark_tpu.analysis",
        description="coslint: JAX/concurrency static analysis "
                    "(rules COS001..COS005)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the "
                         "caffeonspark_tpu package)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON; findings listed there do not "
                         "fail the run (artifacts/coslint_baseline.json)")
    ap.add_argument("--write-baseline", default=None, metavar="PATH",
                    help="write current findings as the new baseline "
                         "and exit 0")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.id}  {r.title}")
            doc = (r.__doc__ or "").strip()
            for line in doc.splitlines():
                print(f"    {line.strip()}")
            print()
        return 0

    result = run_lint(args.paths or None)

    if args.write_baseline:
        os.makedirs(os.path.dirname(args.write_baseline) or ".",
                    exist_ok=True)
        write_baseline(args.write_baseline, result)
        print(f"coslint: baseline with {len(result.findings)} "
              f"finding(s) -> {args.write_baseline}")
        return 0

    baselined = set()
    if args.baseline and os.path.exists(args.baseline):
        baselined = load_baseline(args.baseline)
    fresh = [f for f in result.findings if f.key not in baselined]
    stale = baselined - baseline_keys(result.findings)

    if args.json:
        print(json.dumps({
            "files": result.files,
            "suppressed": result.suppressed,
            "findings": [{"rule": f.rule, "path": f.path,
                          "line": f.line, "col": f.col,
                          "message": f.message} for f in fresh],
            "baselined": len(result.findings) - len(fresh),
        }, indent=2))
    else:
        for f in fresh:
            print(f.render())
        print(f"coslint: {result.files} file(s), "
              f"{len(fresh)} finding(s)"
              f" ({len(result.findings) - len(fresh)} baselined, "
              f"{result.suppressed} suppressed in source)")
        if stale:
            print(f"coslint: note — {len(stale)} baseline entr"
                  f"{'y is' if len(stale) == 1 else 'ies are'} no "
                  "longer produced (baseline can be re-written)")
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
