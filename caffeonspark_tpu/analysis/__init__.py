"""coslint: static analysis + runtime trace-safety guards.

Every hard bug this repo has shipped a fix for belongs to a
mechanically detectable class — CPU `device_put` host-buffer aliasing
(the PR 3 ingest hazard), silent MXU precision loss on f32-consuming
einsums (the sp.py ring-backward fix), trace-time host reads baked
into jitted programs, use-after-donation, and locks held across
blocking calls in the threaded runtime.  This package is the
correctness-tooling layer that keeps those classes out:

  * `coslint` / `rules` — an AST linter with rules COS001..COS005,
    run as `python -m caffeonspark_tpu.analysis` (or `make lint`) and
    enforced by the tier-1 suite (tests/test_coslint.py) against the
    checked-in baseline `artifacts/coslint_baseline.json`;
  * `runtime` — `RecompileGuard` (fails when steady state recompiles,
    `COS_RECOMPILE_GUARD=1`), a debug-mode donation poisoner
    (`COS_DONATION_POISON=1`), and `LockWitness`, the runtime
    lock-order/race witness behind COS005's stress tests.

Suppression syntax (see coslint.py): `# coslint: disable=COS001` on
the flagged line (or the enclosing `def` line), and
`# coslint: disable-file=COS003` for a whole module — always with a
trailing reason.
"""

from .coslint import (Finding, LintResult, baseline_keys, load_baseline,
                      run_lint, write_baseline)
from .rules import ALL_RULES, Rule
from .runtime import (LockOrderError, LockWitness, RecompileError,
                      RecompileGuard, maybe_poison_donation,
                      maybe_recompile_guard, poison_donation)

__all__ = [
    "Finding", "LintResult", "run_lint", "load_baseline",
    "write_baseline", "baseline_keys", "ALL_RULES", "Rule",
    "RecompileGuard", "RecompileError", "maybe_recompile_guard",
    "poison_donation", "maybe_poison_donation",
    "LockWitness", "LockOrderError",
]
