"""Importable per-layer roofline model (lifted out of scripts/roofline.py).

For every compute layer of a constructed Net, bounds one train step's
time by max(FLOPs / MXU peak, HBM bytes / bandwidth) and classifies the
layer as MXU-bound or HBM-bound — the ranking the per-layer autotuner
(`ops/autotune.py`) prunes its variant search with, and the model the
CLI report (`scripts/roofline.py`, now a shim over this module) prints.

Model (estimate-grade, stated so the numbers are auditable):
  * forward bytes/layer = in + out activations + params read;
  * backward ≈ 2x forward traffic (dL/dx needs weights + stashed
    activations; dL/dW needs activations + writes grads) and 2x
    forward FLOPs for weighted layers;
  * optimizer: read param+momentum, write param+momentum in f32
    (16 bytes/param) regardless of compute dtype;
  * fused=True drops elementwise layers' activation traffic (XLA fuses
    ReLU/Dropout/eltwise into the producing matmul/conv) — the fused
    and unfused totals bracket reality;
  * a per-layer `variants` map (the autotuner's plan shape) adjusts the
    accounting: a bf16 dtype variant halves that layer's activation and
    param-read bytes, an int8 variant quarters the param read, and an
    LRN fusion variant drops the fused ReLU's (and deferred bias-add's)
    separate round trip — so a candidate plan can be costed without
    building it.

MODEL_VERSION bumps whenever the accounting above changes; JSON
emitters carry it (plus SCHEMA) so downstream consumers can detect
model changes instead of silently comparing incompatible estimates.
"""

from __future__ import annotations

from math import prod
from typing import Dict, List, Optional

SCHEMA = "cos-roofline"
MODEL_VERSION = 2          # v1: scripts/roofline.py inline model;
#                            v2: importable + per-layer variant costing

ELEMENTWISE = {"ReLU", "Dropout", "Eltwise", "Scale", "Bias", "PReLU",
               "Sigmoid", "TanH", "ELU", "AbsVal", "Power", "Exp",
               "Log", "BNLL"}
MEMBOUND = {"Pooling", "LRN", "Softmax", "SoftmaxWithLoss", "Concat",
            "Slice", "Flatten", "Reshape", "BatchNorm", "Accuracy"}

# bf16 peak TFLOP/s per chip by device_kind substring (public spec
# sheets); MFU is reported against the RUNNING chip's peak, not a
# hard-coded generation, so committed evidence is self-describing.
# One copy: bench.py and scripts/bench_attention.py both resolve
# through here.
PEAK_BF16_TFLOPS = (
    ("v6e", 918.0), ("trillium", 918.0),
    ("v5p", 459.0),
    ("v5e", 197.0), ("v5 lite", 197.0), ("v5litepod", 197.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)

# the explicitly-labeled reference chip callers fall back to when the
# device_kind matches no known chip (v5e)
FALLBACK_PEAK_TFLOPS = 197.0


def peak_tflops_for_kind(device_kind: str) -> tuple:
    """(peak_bf16_tflops, source) for a device_kind string, or
    (None, 'unknown') when it matches no known chip — callers then
    fall back to an explicitly-labeled v5e reference."""
    kind = str(device_kind or "").lower()
    for sub, peak in PEAK_BF16_TFLOPS:
        if sub in kind:
            return peak, f"device_kind:{kind}"
    return None, "unknown"


def peak_tflops(device) -> tuple:
    """(peak_bf16_tflops, source) for a jax device object (reads its
    device_kind attribute)."""
    return peak_tflops_for_kind(getattr(device, "device_kind", ""))


def _variant_bytes(variant: Optional[dict], act_bytes: int,
                   param_bytes: int) -> tuple:
    """(act_bytes, param_bytes) under a layer's autotune variant."""
    if not variant:
        return act_bytes, param_bytes
    dt = variant.get("dtype")
    if dt == "bfloat16":
        act_bytes, param_bytes = 2, 2
    elif dt == "float32":
        act_bytes, param_bytes = 4, 4
    if variant.get("int8"):
        param_bytes = 1
    return act_bytes, param_bytes


def analyze_net(net, *, act_bytes: int, param_bytes: int,
                fused: bool = False,
                variants: Optional[Dict[str, dict]] = None
                ) -> List[dict]:
    """Per-layer {layer, type, flops, bytes, params} rows for one TRAIN
    step of a constructed Net (see module docstring for the model).
    `variants` is an autotune-plan-shaped {layer: variant} map used to
    cost a candidate plan without building it."""
    from ..utils.flops import layer_forward_flops
    variants = variants or {}
    per_layer = layer_forward_flops(net)
    # an LRN fuse variant on an UNFUSED net absorbs the feeding ReLU
    # into the LRN's epilogue: that relu row's traffic disappears.  (On
    # a net already built with the fusion the relu layer is gone from
    # compute_layers, so the saving shows up with no variant at all —
    # both costings agree.)  Eligibility is net.py's OWN peephole
    # predicate — crediting a fusion the build would refuse would let
    # an inert variant fake an uplift under the injected-floor regime.
    from ..net import fusable_relu_for_lrn
    fused_relus = set()
    layers = list(net.compute_layers)
    for lp in layers:
        if lp.type != "LRN":
            continue
        if (variants.get(lp.name) or {}).get("fuse") not in (
                "relu", "bias_relu"):
            continue
        relu = fusable_relu_for_lrn(layers, lp)
        if relu is not None:
            fused_relus.add(relu.name)
    rows = []
    for lp in layers:
        tops = net._top_shapes.get(lp.name, {})
        out_elems = sum(prod(s) for s in tops.values())
        in_elems = sum(prod(net.blob_shapes[b]) for b in lp.bottom
                       if b in net.blob_shapes)
        p_elems = sum(prod(s) for _, s, _ in
                      net.param_layout.get(lp.name, []))
        flops = per_layer.get(lp.name, 0)
        ab, pb = _variant_bytes(variants.get(lp.name), act_bytes,
                                param_bytes)
        fwd_bytes = (in_elems + out_elems) * ab + p_elems * pb
        if lp.type in ELEMENTWISE and (fused
                                       or lp.name in fused_relus):
            fwd_bytes = 0          # fused into the producer's epilogue
        step_bytes = 3 * fwd_bytes + 16 * p_elems
        step_flops = 3 * flops
        rows.append({"layer": lp.name, "type": lp.type,
                     "flops": step_flops, "bytes": step_bytes,
                     "params": p_elems})
    return rows


def classify(rows: List[dict], *, peak_tflops: float = None,
             hbm_gbs: float = 819.0) -> List[dict]:
    """Adds t_flop_us / t_mem_us / bound / t_us to each row (in place)
    and returns the rows sorted DESCENDING by roofline time — the
    autotuner's pruning order.  Defaults model the v5e reference."""
    peak = (peak_tflops or FALLBACK_PEAK_TFLOPS) * 1e12
    bw = hbm_gbs * 1e9
    for r in rows:
        r["t_flop_us"] = r["flops"] / peak * 1e6
        r["t_mem_us"] = r["bytes"] / bw * 1e6
        r["bound"] = ("mxu" if r["t_flop_us"] >= r["t_mem_us"]
                      else "hbm")
        r["t_us"] = max(r["t_flop_us"], r["t_mem_us"])
    return sorted(rows, key=lambda r: r["t_us"], reverse=True)


def step_bytes_total(net, *, act_bytes: int = 2, param_bytes: int = 2,
                     variants: Optional[Dict[str, dict]] = None) -> int:
    """Total modeled HBM bytes of one train step under a (possibly
    empty) variant plan — the quantity the autotune bench's injected
    HBM-floor regime sleeps proportionally to."""
    rows = analyze_net(net, act_bytes=act_bytes, param_bytes=param_bytes,
                       fused=False, variants=variants)
    return sum(r["bytes"] for r in rows)
