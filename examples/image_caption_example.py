"""LRCN image-caption inference — the ImageCaption.py example of the
reference (SURVEY §2.8): load a trained captioner, embed images to
features, greedily decode captions.

Run (after training an LRCN model and building a vocab):
    python examples/image_caption_example.py \
        -net word_to_preds.deploy.prototxt \
        -weights lrcn.caffemodel -vocabDir vocab/ \
        -embeddingDFDir embdf/
"""

import argparse
import sys

import numpy as np


def main(argv=None):
    from caffeonspark_tpu import checkpoint
    from caffeonspark_tpu.net import Net
    from caffeonspark_tpu.proto import NetState, Phase, read_net
    from caffeonspark_tpu.tools import Vocab
    from caffeonspark_tpu.tools.image_caption import (captions_to_text,
                                                      greedy_caption)

    p = argparse.ArgumentParser()
    p.add_argument("-net", required=True)
    p.add_argument("-weights", required=True)
    p.add_argument("-vocabDir", required=True)
    p.add_argument("-embeddingDFDir", required=True,
                   help="parquet with image feature vectors")
    p.add_argument("-featureColumn", default="image_features")
    p.add_argument("-captionLength", type=int, default=20)
    p.add_argument("-beam", type=int, default=1,
                   help="beam width (1 = greedy incremental decode)")
    a = p.parse_args(argv if argv is not None else sys.argv[1:])

    import jax
    net = Net(read_net(a.net), NetState(phase=Phase.TEST))
    params = net.init(jax.random.key(0))
    params = checkpoint.copy_layers(net, params, a.weights)
    vocab = Vocab.load(a.vocabDir)

    import pyarrow.parquet as pq
    t = pq.read_table(a.embeddingDFDir)
    feats = np.asarray(t.column(a.featureColumn).to_pylist(), np.float32)
    if a.beam > 1:
        from caffeonspark_tpu.tools.image_caption import beam_caption
        seqs = beam_caption(read_net(a.net), params,
                            {a.featureColumn: feats},
                            batch=feats.shape[0], beam=a.beam,
                            max_length=a.captionLength)
    else:
        seqs = greedy_caption(net, params, feats,
                              max_length=a.captionLength)
    for i, text in enumerate(captions_to_text(seqs, vocab)):
        print(f"{i}: {text}")


if __name__ == "__main__":
    main()
