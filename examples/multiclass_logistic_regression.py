"""DL-features → logistic regression — the MyMLPipeline /
MultiClassLogisticRegression.py example of the reference (SURVEY §2.8):
extract deep features with a trained convnet, then fit a linear
classifier on them (numpy softmax regression stands in for MLlib LR).

Run:
    python examples/multiclass_logistic_regression.py \
        -conf solver.prototxt -weights model.caffemodel \
        -features ip1 -label label
"""

import sys

import numpy as np


def softmax_regression(X, y, *, num_classes, lr=0.1, epochs=200):
    n, d = X.shape
    W = np.zeros((d, num_classes), np.float32)
    b = np.zeros((num_classes,), np.float32)
    yi = y.astype(int)
    for _ in range(epochs):
        z = X @ W + b
        z -= z.max(axis=1, keepdims=True)
        p = np.exp(z)
        p /= p.sum(axis=1, keepdims=True)
        p[np.arange(n), yi] -= 1.0
        W -= lr * (X.T @ p) / n
        b -= lr * p.mean(axis=0)
    return W, b


def main(argv=None):
    from caffeonspark_tpu.caffe_on_spark import CaffeOnSpark
    from caffeonspark_tpu.config import Config
    from caffeonspark_tpu.data import get_source

    conf = Config(argv if argv is not None else sys.argv[1:])
    if not conf.features:
        conf.features = "ip1"
    if not conf.label:
        conf.label = "label"
    cos = CaffeOnSpark()
    layer = conf.test_data_layer() or conf.train_data_layer()
    src = get_source(layer, phase_train=False, resize=conf.resize)
    df = cos.features(src, conf)

    feat_col = conf.features.split(",")[0]
    X = np.asarray([r[feat_col] for r in df.rows], np.float32)
    y = np.asarray([r[conf.label][0] for r in df.rows], np.float32)
    num_classes = int(y.max()) + 1
    W, b = softmax_regression(X, y, num_classes=num_classes)
    acc = float(((X @ W + b).argmax(axis=1) == y.astype(int)).mean())
    print(f"logistic regression on {feat_col}: {len(df)} samples, "
          f"{X.shape[1]} dims, {num_classes} classes, "
          f"train accuracy {acc:.4f}")
    return acc


if __name__ == "__main__":
    main()
