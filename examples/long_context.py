"""Long-context training demo: sequence parallelism end to end.

    python examples/long_context.py [seq_len]

Trains the zoo's causal transformer LM on synthetic token streams with
the TIME axis sharded over an `sp` mesh (ring attention semantics —
the capability the reference lacks entirely, SURVEY §2.7/§5.7) and
prints the loss curve plus a parity check against the unsharded step.
Runs anywhere: on CPU it builds a virtual 8-device mesh
(`XLA_FLAGS=--xla_force_host_platform_device_count=8`); on a TPU pod
slice the same code shards over real chips, and 128-aligned sequence
lengths dispatch MultiHeadAttention to the Pallas flash kernel
(O(block·T) VMEM) automatically.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))      # run in-repo without install


def main(seq_len: int = 32):
    import jax
    if "xla_force_host_platform_device_count" in os.environ.get(
            "XLA_FLAGS", ""):
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from caffeonspark_tpu.models import transformer_lm
    from caffeonspark_tpu.parallel import ParallelSolver, build_mesh
    from caffeonspark_tpu.proto import SolverParameter
    from caffeonspark_tpu.solver import Solver

    n_dev = len(jax.devices())
    sp_n = max(s for s in (1, 2, 4) if n_dev % s == 0 and s <= seq_len)
    dp_n = max(1, n_dev // sp_n)
    batch = 2 * dp_n
    print(f"devices={n_dev}  mesh dp={dp_n} x sp={sp_n}  "
          f"seq={seq_len}  batch={batch}")

    npm = transformer_lm(vocab=64, d_model=32, heads=2, layers=2,
                         seq=seq_len, batch=batch)
    sp_txt = ("base_lr: 0.01 momentum: 0.9 lr_policy: 'fixed' "
              "type: 'ADAM' random_seed: 5")

    rng = np.random.RandomState(0)
    seqs = rng.randint(0, 60, (seq_len, batch)).astype(np.float32)
    data = {"input_sentence": jnp.asarray(seqs),
            "target_sentence": jnp.asarray((seqs + 1) % 60)}

    # sequence-parallel step: T sharded over sp, batch over dp
    mesh = build_mesh(dp=dp_n, sp=sp_n)
    solver = Solver(SolverParameter.from_text(sp_txt), npm)
    ps = ParallelSolver(solver, mesh)
    sh = NamedSharding(mesh, P("sp", "dp"))
    params, st = ps.init()
    step = jax.jit(
        solver.train_step_fn(), donate_argnums=(0, 1),
        in_shardings=(ps.param_sharding,
                      type(st)(iter=ps.repl, history=ps.param_sharding,
                               history2=ps.param_sharding),
                      {k: sh for k in data}, ps.repl))

    # unsharded reference for the parity line
    ref = Solver(SolverParameter.from_text(sp_txt), npm)
    p_ref, st_ref = ref.init()
    step_ref = ref.jit_train_step()

    sharded = {k: jax.device_put(v, sh) for k, v in data.items()}
    for i in range(10):
        r = solver.step_rng(i)
        params, st, out = step(params, st, sharded, r)
        p_ref, st_ref, out_ref = step_ref(p_ref, st_ref, data, r)
        loss = float(jax.device_get(out["loss"]))
        delta = abs(loss - float(jax.device_get(out_ref["loss"])))
        print(f"iter {i:2d}  loss {loss:.4f}  "
              f"|sp - single-device| = {delta:.2e}")
        assert delta < 1e-3 * max(1.0, abs(loss))
    print("sequence-parallel training matches the single-device step")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 32)
