"""Long-context training demo: sequence parallelism end to end.

    python examples/long_context.py [seq_len]

Trains the zoo's causal transformer LM on synthetic token streams with
the TIME axis sharded over an `sp` mesh (the capability the reference
lacks entirely, SURVEY §2.7/§5.7) and prints the loss curve plus a
parity check against the unsharded step.  With no accelerator the
script builds a virtual 8-device CPU mesh itself; on a TPU pod slice
the same code shards over real chips.  On TPU meshes the attention
dispatch routes through shard_map automatically: dp/tp meshes run the
Pallas flash kernel per (batch, heads) block, and sp meshes run the
DIFFERENTIABLE fused ring (K/V rotating on ICI, flash kernels per
hop) when the local sequence extent is kernel-eligible — einsum
otherwise.  `parallel.sp.ring_attention(flash=True)` exposes the same
fused ring for hand-rolled steps (demoed below).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))      # run in-repo without install

# no accelerator → virtual 8-device CPU mesh, BEFORE jax initializes
if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", "") and not os.environ.get("COS_REAL_DEVICES"):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device"
                                 "_count=8").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)


def main(seq_len: int = 32):
    import jax
    if "xla_force_host_platform_device_count" in os.environ.get(
            "XLA_FLAGS", ""):
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from caffeonspark_tpu.models import transformer_lm
    from caffeonspark_tpu.parallel import ParallelSolver, build_mesh
    from caffeonspark_tpu.proto import SolverParameter
    from caffeonspark_tpu.solver import Solver

    n_dev = len(jax.devices())
    sp_n = max(s for s in (1, 2, 4)
               if n_dev % s == 0 and seq_len % s == 0)
    dp_n = max(1, n_dev // sp_n)
    batch = 2 * dp_n
    print(f"devices={n_dev}  mesh dp={dp_n} x sp={sp_n}  "
          f"seq={seq_len}  batch={batch}")

    npm = transformer_lm(vocab=64, d_model=32, heads=2, layers=2,
                         seq=seq_len, batch=batch)
    sp_txt = ("base_lr: 0.01 momentum: 0.9 lr_policy: 'fixed' "
              "type: 'ADAM' random_seed: 5")

    rng = np.random.RandomState(0)
    seqs = rng.randint(0, 60, (seq_len, batch)).astype(np.float32)
    data = {"input_sentence": jnp.asarray(seqs),
            "target_sentence": jnp.asarray((seqs + 1) % 60)}

    # sequence-parallel step: ParallelSolver shards time-major inputs
    # (T, B, ·) as P("sp", "dp") on an sp mesh — no hand-rolled jit
    solver = Solver(SolverParameter.from_text(sp_txt), npm)
    ps = ParallelSolver(solver, build_mesh(dp=dp_n, sp=sp_n))
    params, st = ps.init()
    step = ps.train_step()

    # unsharded reference for the parity line
    ref = Solver(SolverParameter.from_text(sp_txt), npm)
    p_ref, st_ref = ref.init()
    step_ref = ref.jit_train_step()

    for i in range(10):
        r = solver.step_rng(i)
        params, st, out = step(params, st, ps.shard_batch(data), r)
        p_ref, st_ref, out_ref = step_ref(p_ref, st_ref, data, r)
        loss = float(jax.device_get(out["loss"]))
        delta = abs(loss - float(jax.device_get(out_ref["loss"])))
        print(f"iter {i:2d}  loss {loss:.4f}  "
              f"|sp - single-device| = {delta:.2e}")
        assert delta < 1e-3 * max(1.0, abs(loss))
    print("sequence-parallel training matches the single-device step")

    if sp_n > 1:
        _fused_ring_demo(sp_n, dp_n, seq_len)


def _fused_ring_demo(sp_n: int, dp_n: int, seq_len: int):
    """Hand-rolled long-context step on the DIFFERENTIABLE fused ring:
    ring_attention(flash=...) trains with per-hop Pallas kernels (the
    custom-VJP second ring pass) — interpret-mode on CPU meshes, the
    compiled Mosaic kernels on a real pod."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from caffeonspark_tpu.parallel import build_mesh
    from caffeonspark_tpu.parallel.sp import ring_attention

    mesh = build_mesh(dp=dp_n, sp=sp_n)
    flash = "interpret" if jax.default_backend() == "cpu" else True
    rng = np.random.RandomState(1)
    b, h, d = 2, 2, 16
    t = max(seq_len, 8 * sp_n)
    x = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    tgt = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    w = jnp.asarray(rng.randn(d, d) * 0.3, jnp.float32)

    @jax.jit
    def ring_step(w):
        def loss(w):
            qkv = jnp.einsum("bhtd,de->bhte", x, w)
            out = ring_attention(qkv, qkv, qkv, mesh, causal=True,
                                 flash=flash)
            return jnp.mean((out - tgt) ** 2)
        l, g = jax.value_and_grad(loss)(w)
        return w - 0.5 * g, l

    losses = []
    for _ in range(5):
        w, l = ring_step(w)
        losses.append(float(jax.device_get(l)))
    print("fused-ring (differentiable flash) losses: "
          + "  ".join(f"{l:.4f}" for l in losses))
    assert losses[-1] < losses[0] and np.isfinite(losses).all()
    print("fused ring attention trains end to end")

    # cross-attention at long context: K/V twice as long as Q (e.g. a
    # decoder attending a long encoder memory) — unequal per-shard
    # extents route through the cross-extent fused ring (fused Pallas
    # forward, einsum-ring backward) and still train
    t_kv = 2 * t
    mem = jnp.asarray(rng.randn(b, h, t_kv, d), jnp.float32)
    wq = jnp.asarray(rng.randn(d, d) * 0.3, jnp.float32)

    @jax.jit
    def cross_step(wq):
        def loss(wq):
            q = jnp.einsum("bhtd,de->bhte", x, wq)
            out = ring_attention(q, mem, mem, mesh, flash=flash)
            return jnp.mean((out - tgt) ** 2)
        l, g = jax.value_and_grad(loss)(wq)
        return wq - 0.5 * g, l

    closses = []
    for _ in range(5):
        wq, l = cross_step(wq)
        closses.append(float(jax.device_get(l)))
    print(f"cross-attention fused ring (T_q={t}, T_kv={t_kv}) losses: "
          + "  ".join(f"{l:.4f}" for l in closses))
    assert closses[-1] < closses[0] and np.isfinite(closses).all()
    print("cross-extent fused ring trains end to end")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 32)
