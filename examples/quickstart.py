"""Quickstart: synthetic dataset → LMDB → train → validate → test →
feature extraction, end to end in one script (no flags needed).

    python examples/quickstart.py [workdir]

Demonstrates the full reference workflow on generated data: builds an
MNIST-shaped LMDB with the bulk writer, writes solver/net prototxts,
trains LeNet with interleaved validation through the CaffeOnSpark
facade, runs test() means and features() extraction, and reloads the
snapshot for finetuning."""

import os
import sys
import tempfile

import numpy as np


def main(workdir=None):
    from caffeonspark_tpu.caffe_on_spark import CaffeOnSpark
    from caffeonspark_tpu.config import Config
    from caffeonspark_tpu.data import LmdbWriter, get_source
    from caffeonspark_tpu.data.synthetic import make_images
    from caffeonspark_tpu.models.zoo import LENET
    from caffeonspark_tpu.proto.caffe import Datum

    work = workdir or tempfile.mkdtemp(prefix="cos_quickstart_")
    os.makedirs(work, exist_ok=True)
    print(f"workdir: {work}")

    # 1. dataset → LMDB (setup-mnist.sh analog, synthetic)
    for split, n, seed in (("train", 512, 1), ("test", 128, 99)):
        imgs, labels = make_images(n, seed=seed)
        recs = [(b"%08d" % i,
                 Datum(channels=1, height=28, width=28,
                       data=(imgs[i, 0] * 255).astype(np.uint8)
                       .tobytes(), label=int(labels[i])).to_binary())
                for i in range(n)]
        LmdbWriter(os.path.join(work, f"{split}_lmdb")).write(recs)
    print("LMDBs written")

    # 2. configs: parse the zoo LeNet, point its data layer at the
    # train LMDB, and clone a TEST-phase twin for the test LMDB
    from caffeonspark_tpu.proto import parse_net_prototxt
    from caffeonspark_tpu.proto.caffe import Phase
    npm = parse_net_prototxt(LENET)
    data = next(l for l in npm.layer if l.type == "MemoryData")
    from caffeonspark_tpu.proto.caffe import NetStateRule
    data.source_class = "LMDB"
    data.memory_data_param.source = os.path.join(work, "train_lmdb")
    data.memory_data_param.batch_size = 32
    data.include.append(NetStateRule(phase=Phase.TRAIN))
    test_data = data.clone()
    test_data.include[0].phase = Phase.TEST
    test_data.memory_data_param.source = os.path.join(work, "test_lmdb")
    npm.layer.insert(1, test_data)
    net_path = os.path.join(work, "lenet.prototxt")
    with open(net_path, "w") as f:
        f.write(npm.to_text())
    solver_path = os.path.join(work, "solver.prototxt")
    with open(solver_path, "w") as f:
        f.write(f"""net: "{net_path}"
test_iter: 4
test_interval: 50
base_lr: 0.01
momentum: 0.9
weight_decay: 0.0005
lr_policy: "inv"
gamma: 0.0001
power: 0.75
display: 50
max_iter: 200
snapshot: 100
snapshot_prefix: "lenet"
random_seed: 42
""")

    # 3. train with interleaved validation
    conf = Config(["-conf", solver_path, "-train", "-output", work])
    cos = CaffeOnSpark()
    train_src = get_source(conf.train_data_layer(), phase_train=True)
    val_src = get_source(conf.test_data_layer(), phase_train=False)
    vdf = cos.trainWithValidation(train_src, val_src, conf)
    print("validation rounds:",
          [{k: round(v, 4) for k, v in r.items()} for r in vdf.rows])

    # 4. test(): per-output means over the test set
    conf.modelPath = os.path.join(work, "model.caffemodel")
    from caffeonspark_tpu import checkpoint
    from caffeonspark_tpu.processor import CaffeProcessor
    proc = CaffeProcessor.instance()
    checkpoint.save_caffemodel(conf.modelPath, proc.solver.train_net,
                               proc.params)
    result = cos.test(val_src, conf)
    print("test():", {k: [round(x, 4) for x in v[:3]]
                      for k, v in result.items()})

    # 5. features(): SampleID + blobs DataFrame → json
    fconf = Config(["-conf", solver_path, "-features", "ip1,ip2",
                    "-label", "label",
                    "-weights", conf.modelPath])
    fdf = cos.features(val_src, fconf)
    out = os.path.join(work, "features.json")
    fdf.write(out, "json")
    print(f"features: {len(fdf)} rows → {out}")

    acc = result.get("accuracy", [0.0])[0]
    print(f"final test accuracy: {acc:.4f}")
    return acc


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else None)
